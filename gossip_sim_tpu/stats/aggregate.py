"""Cross-origin aggregate statistics for ``--all-origins`` mode.

The single-origin path harvests per-iteration detail arrays and feeds the
reference-shaped ``GossipStats`` (gossip_stats.rs:1228-1884).  At all-origins
scale (N origins x iterations) that would mean shipping [O, N] detail off
device every round, so the engine instead accumulates everything on device —
``hops_hist_acc``, ``stranded_acc``, ``egress/ingress/prune_acc`` plus the
per-round scalar rows — and this module turns those accumulators into the
same statistics the reference prints and reports: coverage/RMR collections
(gossip_stats.rs:229-347), aggregate-hop and last-delivery-hop stats
(gossip_stats.rs:27-227), the 11 stranded-node stats (gossip_stats.rs:
964-1038), branching factor, and the stake-bucketed message histograms
(gossip_stats.rs:359-461).

Divergence note: aggregate hop mean/median/max come from the on-device hop
histogram, whose top bin clamps hops >= hist_bins-1 (64 by default, far
above the ~11-hop diameters seen in practice, README.md:232-241).
"""

from __future__ import annotations

import logging

import numpy as np

from .collections import StatCollection
from .histogram import Histogram
from .hops import HopsStat
from .stranded import StrandedNodeCollection
from .trackers import EgressIngressMessageTracker

log = logging.getLogger(__name__)


def lane_rows(rows: dict, lane: int) -> dict:
    """One sweep lane's rows out of a lane-batched engine harvest.

    ``run_rounds_lanes`` (engine/lanes.py) returns rows with a lane axis
    after the iteration axis — every leaf is ``[iters, K, ...]`` where a
    serial ``run_rounds`` harvest is ``[iters, ...]``.  Slicing one lane
    restores exactly the serial shape, so the per-sim stats feeders
    (cli._feed_measured_round and friends) consume a lane unchanged: the
    lane-batched sweep and the serial sweep flow through one stats path
    and can never drift.  Works on device arrays and the np.asarray'd
    harvest alike."""
    return {k: v[:, lane] for k, v in rows.items()}


class HistogramHopsStat:
    """HopsStat (mean/median/max/min, zeros filtered) computed from binned
    counts instead of raw values (gossip_stats.rs:46-98 semantics)."""

    def __init__(self, counts: np.ndarray):
        counts = np.asarray(counts, dtype=np.int64).copy()
        if counts.size:
            counts[0] = 0               # hop 0 = the origin itself; filtered
        total = int(counts.sum())
        if total == 0:
            self.mean, self.median, self.max, self.min = 0.0, 0.0, 0, 0
            return
        hops = np.arange(counts.size, dtype=np.int64)
        self.mean = float((hops * counts).sum() / total)
        cum = np.cumsum(counts)
        lo_i, hi_i = (total - 1) // 2, total // 2
        lo_v = int(np.searchsorted(cum, lo_i, side="right"))
        hi_v = int(np.searchsorted(cum, hi_i, side="right"))
        self.median = (lo_v + hi_v) / 2.0
        nz = np.nonzero(counts)[0]
        self.max = int(nz[-1])
        self.min = int(nz[0])


class AllOriginsStats:
    """Aggregates engine rows + on-device accumulators across origin batches
    into reference-shaped statistics.

    Per-point series stay as numpy chunks (measured_points reaches ~1e7 at
    the 10k-origins x 1000-iterations target; boxed-float lists would cost
    GBs); finalize() computes the StatCollection summaries vectorized."""

    def __init__(self, index, hist_bins: int):
        self.index = index               # NodeIndex (pubkeys <-> stakes)
        self.N = len(index)
        self.hist_bins = hist_bins
        self.coverage_stats = StatCollection("Coverage")
        self.rmr_stats = StatCollection("RMR")
        self.branching_stats = StatCollection("Outbound Branching Factor")
        self.delivered_stats = StatCollection("Delivered Messages")
        self.dropped_stats = StatCollection("Dropped Messages")
        self.suppressed_stats = StatCollection("Suppressed Messages")
        self.failed_stats = StatCollection("Failed Nodes")
        # pull-phase aggregates (pull.py); empty unless a pull mode ran
        self.pull_requests_stats = StatCollection("Pull Requests")
        self.pull_responses_stats = StatCollection("Pull Responses")
        self.pull_misses_stats = StatCollection("Pull Misses")
        self.pull_rescued_stats = StatCollection("Pull Rescued Nodes")
        self._chunks = {"coverage": [], "rmr": [], "branching": [],
                        "ldh": [], "delivered": [], "dropped": [],
                        "suppressed": [], "failed": [],
                        "pull_requests": [], "pull_responses": [],
                        "pull_misses": [],
                        "pull_rescued": []}  # per-batch [measured*O] arrays
        self.hops_hist = np.zeros(hist_bins, np.int64)
        self.stranded_counts = np.zeros(self.N, np.int64)
        self.egress = np.zeros(self.N, np.int64)
        self.ingress = np.zeros(self.N, np.int64)
        self.prunes = np.zeros(self.N, np.int64)
        self.measured_points = 0         # (round, origin) pairs measured
        self.num_origins = 0
        self.inb_dropped = 0
        self.rc_overflow = 0
        self.hop_clamped = 0             # hops clamped into the top bin
        self.total_dropped = 0           # loss-dropped messages (measured)
        self.total_suppressed = 0        # partition-suppressed (measured)
        self.impaired = False            # set by finalize(config)
        self.pull = False                # a pull mode ran (set by finalize)
        self.total_pull_requests = 0     # arrived pull requests (measured)
        self.total_pull_responses = 0    # pull value transfers (measured)
        self.total_pull_rescued = 0      # pull-rescued (origin, round) pairs
        self.total_pull_dropped = 0      # loss-dropped pull requests
        self.total_pull_suppressed = 0   # partition-suppressed pull requests
        self.pull_hops_hist = np.zeros(hist_bins, np.int64)
        self.pull_rescued_counts = np.zeros(self.N, np.int64)
        # per-origin iterations-to-recover coverage after heal (faults.py);
        # -1 = that origin never recovered within the run
        self.recovery_iters = []
        # filled by finalize():
        self.aggregate_hops = HopsStat()
        self.ldh_stats = HopsStat()
        self.stranded = StrandedNodeCollection()
        self.hops_histogram = Histogram()
        self.egress_tracker = EgressIngressMessageTracker()
        self.ingress_tracker = EgressIngressMessageTracker()
        self.prune_tracker = EgressIngressMessageTracker()

    # -- per-batch accumulation -------------------------------------------

    def add_batch(self, rows, state, warm_up_rounds: int, heal_at: int = -1,
                  impaired: bool = False, pull: bool = False):
        """Fold one origin batch's rows (leading [iters] axis) + final
        SimState accumulators (already warm-up-gated on device).

        ``heal_at`` >= 0 additionally extracts per-origin
        iterations-to-recover-coverage from the full (unwarmed) coverage
        series.  ``impaired`` gates the delivery-counter accumulation —
        the engine always emits the counter rows (all-zero when the knobs
        are off), so unimpaired runs must not retain them.  ``pull`` gates
        the pull-phase counters (pull.py) the same way."""
        cov = np.asarray(rows["coverage"])[warm_up_rounds:]
        if cov.size:
            self._chunks["coverage"].append(
                cov.ravel().astype(np.float64))
            self._chunks["rmr"].append(
                np.asarray(rows["rmr"])[warm_up_rounds:]
                .ravel().astype(np.float64))
            self._chunks["branching"].append(
                np.asarray(rows["branching"])[warm_up_rounds:]
                .ravel().astype(np.float64))
            self._chunks["ldh"].append(
                np.asarray(rows["hop_max"])[warm_up_rounds:]
                .ravel().astype(np.int64))
            if impaired:
                for key, row_key in (("delivered", "delivered"),
                                     ("dropped", "dropped"),
                                     ("suppressed", "suppressed"),
                                     ("failed", "failed_count")):
                    self._chunks[key].append(
                        np.asarray(rows[row_key])[warm_up_rounds:]
                        .ravel().astype(np.float64))
            if pull:
                for key in ("pull_requests", "pull_responses",
                            "pull_misses", "pull_rescued"):
                    self._chunks[key].append(
                        np.asarray(rows[key])[warm_up_rounds:]
                        .ravel().astype(np.float64))
        if impaired:
            self.total_dropped += int(
                np.asarray(rows["dropped"])[warm_up_rounds:].sum())
            self.total_suppressed += int(
                np.asarray(rows["suppressed"])[warm_up_rounds:].sum())
        if pull:
            self.total_pull_requests += int(
                np.asarray(rows["pull_requests"])[warm_up_rounds:].sum())
            self.total_pull_responses += int(
                np.asarray(rows["pull_responses"])[warm_up_rounds:].sum())
            self.total_pull_rescued += int(
                np.asarray(rows["pull_rescued"])[warm_up_rounds:].sum())
            self.total_pull_dropped += int(
                np.asarray(rows["pull_dropped"])[warm_up_rounds:].sum())
            self.total_pull_suppressed += int(
                np.asarray(rows["pull_suppressed"])[warm_up_rounds:].sum())
            self.pull_hops_hist += np.asarray(
                state.pull_hops_hist_acc, dtype=np.int64).sum(axis=0)
            self.pull_rescued_counts += np.asarray(
                state.pull_rescued_acc, dtype=np.int64).sum(axis=0)
        if "hop_clamped" in rows:
            # measured rounds only, matching the warm-up-gated hops
            # histogram this guard is about (and the single-origin path)
            self.hop_clamped += int(
                np.asarray(rows["hop_clamped"])[warm_up_rounds:].sum())
        if heal_at >= 0:
            from ..constants import COVERAGE_RECOVERY_THRESHOLD
            cov_full = np.asarray(rows["coverage"])       # [iters, O]
            after = cov_full[heal_at:] >= COVERAGE_RECOVERY_THRESHOLD
            if after.shape[0]:
                hit = after.any(axis=0)
                first = after.argmax(axis=0)
                self.recovery_iters.extend(
                    int(first[o]) if hit[o] else -1
                    for o in range(after.shape[1]))
        self.hops_hist += np.asarray(state.hops_hist_acc,
                                     dtype=np.int64).sum(axis=0)
        self.stranded_counts += np.asarray(state.stranded_acc,
                                           dtype=np.int64).sum(axis=0)
        self.egress += np.asarray(state.egress_acc, np.int64).sum(axis=0)
        self.ingress += np.asarray(state.ingress_acc, np.int64).sum(axis=0)
        self.prunes += np.asarray(state.prune_acc, np.int64).sum(axis=0)
        self.inb_dropped += int(np.asarray(rows["inb_dropped"]).sum())
        self.rc_overflow += int(np.asarray(rows["rc_overflow"]).sum())
        self.measured_points += int(cov.size)
        self.num_origins += int(np.asarray(rows["coverage"]).shape[-1])

    # -- resumable snapshot (resilience.py sidecar) -----------------------

    _SCALAR_STATE = ("measured_points", "num_origins", "inb_dropped",
                     "rc_overflow", "hop_clamped", "total_dropped",
                     "total_suppressed", "total_pull_requests",
                     "total_pull_responses", "total_pull_rescued",
                     "total_pull_dropped", "total_pull_suppressed")
    _ARRAY_STATE = ("hops_hist", "stranded_counts", "egress", "ingress",
                    "prunes", "pull_hops_hist", "pull_rescued_counts")

    def state_dict(self) -> dict:
        """Everything ``add_batch`` has accumulated, as npz-ready arrays.
        The all-origins journal (cli.run_all_origins) snapshots this after
        each committed origin batch; ``load_state_dict`` + the remaining
        batches reproduce an uninterrupted run exactly — the per-point
        chunks concatenate to the same series ``finalize`` would see."""
        out = {}
        for f in self._SCALAR_STATE:
            out["scalar." + f] = np.int64(getattr(self, f))
        for f in self._ARRAY_STATE:
            out["array." + f] = np.asarray(getattr(self, f))
        out["array.recovery_iters"] = np.asarray(self.recovery_iters,
                                                 np.int64)
        for k, chunks in self._chunks.items():
            dtype = np.int64 if k == "ldh" else np.float64
            out["chunk." + k] = (np.concatenate(chunks) if chunks
                                 else np.empty(0, dtype))
        return out

    def load_state_dict(self, sd: dict) -> None:
        for f in self._SCALAR_STATE:
            setattr(self, f, int(sd["scalar." + f]))
        for f in self._ARRAY_STATE:
            setattr(self, f, np.asarray(sd["array." + f]))
        self.recovery_iters = [int(v)
                               for v in np.asarray(sd["array.recovery_iters"])]
        for k in self._chunks:
            arr = np.asarray(sd["chunk." + k])
            self._chunks[k] = [arr] if arr.size else []

    # -- end-of-run -------------------------------------------------------

    @staticmethod
    def _fill_stat_collection(sc, arr):
        """Vectorized StatCollection summary (collections.py semantics:
        mean/median with two-middle averaging/max/min)."""
        if arr.size == 0:
            sc.mean = sc.median = float("nan")
            sc.max = sc.min = 0.0
            return
        sc.mean = float(arr.mean())
        sc.median = float(np.median(arr))
        sc.max = float(arr.max())
        sc.min = float(arr.min())

    def finalize(self, config):
        self.impaired = config.impairments_on
        cov = np.concatenate(self._chunks["coverage"]) if \
            self._chunks["coverage"] else np.empty(0)
        self._fill_stat_collection(self.coverage_stats, cov)
        self._fill_stat_collection(
            self.rmr_stats,
            np.concatenate(self._chunks["rmr"]) if self._chunks["rmr"]
            else np.empty(0))
        self._fill_stat_collection(
            self.branching_stats,
            np.concatenate(self._chunks["branching"])
            if self._chunks["branching"] else np.empty(0))
        for sc, key in ((self.delivered_stats, "delivered"),
                        (self.dropped_stats, "dropped"),
                        (self.suppressed_stats, "suppressed"),
                        (self.failed_stats, "failed"),
                        (self.pull_requests_stats, "pull_requests"),
                        (self.pull_responses_stats, "pull_responses"),
                        (self.pull_misses_stats, "pull_misses"),
                        (self.pull_rescued_stats, "pull_rescued")):
            self._fill_stat_collection(
                sc, np.concatenate(self._chunks[key])
                if self._chunks[key] else np.empty(0))
        self.pull = bool(self._chunks["pull_requests"])
        self.aggregate_hops = HistogramHopsStat(self.hops_hist)
        # LDH = HopsStat over per-round maxima (gossip_stats.rs:196-210):
        # filter 0 (rounds where nobody beyond the origin was reached)
        ldh = (np.concatenate(self._chunks["ldh"])
               if self._chunks["ldh"] else np.empty(0, np.int64))
        ldh = ldh[ldh > 0]
        s = HopsStat()
        if ldh.size:
            s.mean = float(ldh.mean())
            s.median = float(np.median(ldh))
            s.max = int(ldh.max())
            s.min = int(ldh.min())
        self.ldh_stats = s

        # Stranded collection from the per-node strand counts; mirrors
        # insert_nodes called once per (origin, measured round)
        # (gossip_stats.rs:1040-1061).
        c = self.stranded
        stakes_arr = self.index.stakes
        c.stranded_nodes = {
            self.index.pubkeys[i]: (int(stakes_arr[i]),
                                    int(self.stranded_counts[i]))
            for i in np.nonzero(self.stranded_counts)[0]}
        c.total_gossip_iterations = self.measured_points
        c.total_nodes = self.N
        c.calculate_stats()
        # a node can be stranded once per (origin sim, measured round), so
        # the count bound is measured_points, not measured rounds
        c.build_histogram(max(self.measured_points, 1), 0,
                          config.num_buckets_for_stranded_node_hist)

        # Aggregate hop histogram, rebucketed to the CLI bound like the
        # single-origin path (gossip_main.rs:567-578).  Rebucket the 64 bin
        # *counts* directly — expanding to raw values would materialize
        # ~origins x rounds x N entries at target scale.
        from ..constants import STANDARD_HISTOGRAM_UPPER_BOUND
        self.hops_histogram.build_from_counts(
            STANDARD_HISTOGRAM_UPPER_BOUND, 0,
            config.num_buckets_for_hops_stats_hist,
            {h: int(c) for h, c in enumerate(self.hops_hist) if h > 0 and c})

        stakes_map = {pk: int(s)
                      for pk, s in zip(self.index.pubkeys, stakes_arr)}
        for tracker, counts in ((self.egress_tracker, self.egress),
                                (self.ingress_tracker, self.ingress),
                                (self.prune_tracker, self.prunes)):
            tracker.counts = {self.index.pubkeys[i]: int(counts[i])
                              for i in range(self.N)}
            tracker.build_histogram(config.num_buckets_for_message_hist,
                                    stakes_map)
            tracker.normalize_message_counts()

    def recovery_summary(self):
        """Aggregate iterations-to-recover-coverage after heal, or None when
        no heal was configured.  ``-1`` entries (never recovered) are counted
        in ``unrecovered`` and excluded from mean/max; with zero recoveries
        mean/max are 0 (``unrecovered == origins`` disambiguates — and the
        Influx line protocol rejects NaN fields)."""
        if not self.recovery_iters:
            return None
        arr = np.asarray(self.recovery_iters, np.int64)
        ok = arr[arr >= 0]
        return {
            "origins": int(arr.size),
            "unrecovered": int((arr < 0).sum()),
            "mean": float(ok.mean()) if ok.size else 0.0,
            "max": int(ok.max()) if ok.size else 0,
        }

    # -- output -----------------------------------------------------------

    def _print_sc(self, sc):
        log.info("%s Mean: %.6f", sc.collection_type, sc.mean)
        log.info("%s Median: %.6f", sc.collection_type, sc.median)
        log.info("%s Max: %.6f", sc.collection_type, sc.max)
        log.info("%s Min: %.6f", sc.collection_type, sc.min)

    def print_all(self):
        """The reference's print_all shape (gossip_stats.rs:1869-1883),
        aggregated over every origin."""
        log.info("|--- ALL-ORIGINS AGGREGATE: %s origins x %s measured "
                 "points ---|", self.num_origins, self.measured_points)
        log.info("|---- COVERAGE STATS ----|")
        self._print_sc(self.coverage_stats)
        log.info("|---- RELATIVE MESSAGE REDUNDANCY (RMR) STATS ----|")
        self._print_sc(self.rmr_stats)
        log.info("|---- AGGREGATE HOP STATS ----|")
        log.info("Aggregate Hops Mean: %.6f", self.aggregate_hops.mean)
        log.info("Aggregate Hops Median: %.2f", self.aggregate_hops.median)
        log.info("Aggregate Hops Max: %s", self.aggregate_hops.max)
        ldh = self.ldh_stats
        log.info("|---- LAST DELIVERY HOP STATS ----|")
        log.info("LDH Mean: %.6f  Median: %.2f  Max: %s  Min: %s",
                 ldh.mean, ldh.median, ldh.max, ldh.min)
        c = self.stranded
        log.info("|---- STRANDED NODE STATS ----|")
        log.info("Total stranded node iterations: %s",
                 c.total_stranded_iterations)
        log.info("Mean iterations a node was stranded: %.6f",
                 c.stranded_iterations_per_node)
        log.info("Mean nodes stranded per iteration: %.6f",
                 c.mean_stranded_per_iteration)
        log.info("Mean iterations a stranded node was stranded: %.6f",
                 c.mean_stranded_iterations_per_stranded_node)
        log.info("Median iterations a stranded node was stranded: %s",
                 c.median_stranded_iterations_per_stranded_node)
        log.info("Mean stake: %.2f  Median stake: %s  Max: %s  Min: %s",
                 c.stranded_node_mean_stake, c.stranded_node_median_stake,
                 c.stranded_node_max_stake, c.stranded_node_min_stake)
        log.info("Mean weighted stake: %.2f  Median weighted stake: %s",
                 c.weighted_stranded_node_mean_stake,
                 c.weighted_stranded_node_median_stake)
        log.info("Total stranded nodes: %s", c.stranded_count())
        log.info("|---- OUTBOUND BRANCHING FACTOR ----|")
        self._print_sc(self.branching_stats)
        if self.impaired:
            log.info("|---- DEGRADED DELIVERY STATS ----|")
            for sc in (self.delivered_stats, self.dropped_stats,
                       self.suppressed_stats, self.failed_stats):
                self._print_sc(sc)
            log.info("Total dropped: %s  Total suppressed: %s",
                     self.total_dropped, self.total_suppressed)
        if self.pull:
            log.info("|---- PULL (ANTI-ENTROPY) STATS ----|")
            for sc in (self.pull_requests_stats, self.pull_responses_stats,
                       self.pull_misses_stats, self.pull_rescued_stats):
                self._print_sc(sc)
            log.info("Pull totals: %s requests, %s responses, %s rescued, "
                     "%s dropped, %s suppressed",
                     self.total_pull_requests, self.total_pull_responses,
                     self.total_pull_rescued, self.total_pull_dropped,
                     self.total_pull_suppressed)
        rec = self.recovery_summary()
        if rec is not None:
            log.info("|---- COVERAGE RECOVERY AFTER HEAL ----|")
            log.info("Origins: %s  Unrecovered: %s  Mean iters: %.2f  "
                     "Max iters: %s", rec["origins"], rec["unrecovered"],
                     rec["mean"], rec["max"])
        if self.hop_clamped:
            log.info("Hop histogram top-bin clamped samples: %s",
                     self.hop_clamped)

    def emit_influx(self, dp_queue, start_ts: str):
        """Aggregate versions of the reference series
        (influx_db.rs:346-602), one point per run."""
        if dp_queue is None:
            return
        from ..sinks import InfluxDataPoint

        dp = InfluxDataPoint(start_ts, 0)
        dp.create_data_point(self.coverage_stats.mean, "coverage")
        dp.create_rmr_data_point((self.rmr_stats.mean, 0, 0))
        dp.create_hops_stat_point(self.aggregate_hops)
        dp.create_data_point(self.branching_stats.mean, "branching_factor")
        c = self.stranded
        dp.create_stranded_iteration_point(
            c.total_stranded_iterations,
            c.stranded_iterations_per_node,
            c.mean_stranded_per_iteration,
            c.mean_stranded_iterations_per_stranded_node,
            c.median_stranded_iterations_per_stranded_node,
            c.weighted_stranded_node_mean_stake,
            c.weighted_stranded_node_median_stake)
        dp.create_histogram_point("stranded_node_histogram", c.histogram)
        dp.create_histogram_point("aggregate_hops_histogram",
                                  self.hops_histogram)
        dp.create_messages_point("egress_message_count",
                                 self.egress_tracker.histogram, 0)
        dp.create_messages_point("ingress_message_count",
                                 self.ingress_tracker.histogram, 0)
        dp.create_messages_point("prune_message_count",
                                 self.prune_tracker.histogram, 0)
        if self.impaired:
            dp.create_delivery_point(
                self.delivered_stats.mean, self.dropped_stats.mean,
                self.suppressed_stats.mean, self.failed_stats.mean)
        if self.pull:
            dp.create_sim_pull_point(
                self.pull_requests_stats.mean, self.pull_responses_stats.mean,
                self.pull_misses_stats.mean,
                round(self.total_pull_dropped
                      / max(self.measured_points, 1), 4),
                round(self.total_pull_suppressed
                      / max(self.measured_points, 1), 4),
                self.pull_rescued_stats.mean)
        rec = self.recovery_summary()
        if rec is not None:
            dp.create_recovery_point(rec["origins"], rec["mean"],
                                     rec["max"], rec["unrecovered"])
        dp_queue.push_back(dp)
