"""Pull-gossip (anti-entropy) primitives, shared by both backends.

The reference simulates only the push protocol — "Pull gossip is explicitly
not simulated" (reference README.md:271-272) — so its coverage/stranded
numbers ignore the anti-entropy path real Solana gossip relies on to heal
exactly the degraded regimes the fault-injection subsystem (faults.py)
creates.  This module adds a deterministic pull phase modeled on Solana's
CRDS pull (gossip/src/crds_gossip_pull.rs):

* Each round where ``it % pull_interval == 0``, every **live** node
  stake-weight-samples ``pull_fanout`` pull peers and sends each a pull
  request carrying a bloom-filter digest of its known set.
* A contacted peer that is live and **holds** the origin value this round
  (it was reached by the push BFS; the origin itself always holds) responds
  with the value — unless the requester's bloom digest claims the requester
  already has it.  A requester that was reached by push genuinely has the
  value in its bloom (no response needed); a requester that was NOT reached
  suffers a bloom **false positive** with probability ``pull_bloom_fp_rate``
  (the responder wrongly filters the value out — a missed rescue).
* Pull deliveries get ``hop = holder_hop + 1`` and are tagged pull-sourced
  in delivery/hop/stranded accounting; they do NOT enter the received-cache
  / prune machinery (prunes are push-path-only in Solana too) and do not
  change the push RMR rows.
* ``pull_request_cap`` > 0 bounds how many arrived requests a peer serves
  per round (Solana caps pull-response bandwidth); excess requests are
  counted as capped misses.  Requests are served in (requester index, slot)
  arrival order — deterministic and identical in both backends.

Determinism contract (the faults.py philosophy): the two backends consume
randomness in different orders, so every pull decision is a *stateless
counter hash* of ``(impair_seed, iteration, node ids)``:

    peer draw   u_class/u_member = u01(fmix32-edge-hash(seed, it, node, slot))
    bloom FP    fmix32-node-hash(seed, it, node)      < fp_rate   * 2^32
    request loss fmix32-edge-hash(seed, it, src, dst) < loss_rate * 2^32

The stake weighting reuses the push machinery's stake-class factorization
(engine/sampler.py): with 25 stake buckets the active-set weight profile
``(min(bucket, k) + 1)^2`` at its top entry ``k = 24`` reduces to
``(bucket + 1)^2`` — a 25-way class CDF plus a uniform within-class draw.
Pull peer selection is origin-independent (a node's pull partner does not
depend on which value it is missing), so one ``[N, pull_fanout]`` draw per
round serves every origin-sim.  The class CDF is computed here in the same
f64-cumsum -> f32 arithmetic as ``build_sampler_tables`` and the uniform
mapping ``u01 = (h >> 8) * 2^-24`` is exactly representable in f32, so the
scalar (oracle) and vectorized (engine) paths agree bit-for-bit.

Per-slot precedence (mirroring the push phase's failed target > partition >
loss): dead requester / self-draw > failed peer > partition suppression >
request loss > arrival; an arrived request is then capped / not-held /
already-held / bloom-FP / answered.

Message accounting mirrors the push phase (only what arrives counts):
an arrived request is 1 egress for the requester and 1 ingress for the
peer; a response is 1 egress for the responder and 1 ingress for the
requester.  Dropped/suppressed requests consume the slot and are counted
(the ``sim_pull`` dropped/suppressed fields) but move no messages;
requests into churn-failed peers likewise consume the slot and move
nothing — they appear only as the ``peer_failed`` trace outcome, not in
any counter (exactly like pushes to failed targets on the push path).

Everything here is numpy-only: importing this module never touches JAX.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .constants import NUM_PUSH_ACTIVE_SET_ENTRIES
from .faults import (edge_u32, node_u32, partition_active, rate_threshold,
                     round_basis, stake_bipartition)
from .identity import stake_buckets_array

NB = NUM_PUSH_ACTIVE_SET_ENTRIES  # 25

# domain-separation salts for the pull hash streams (faults.py convention)
SALT_PULL_CLASS = 0x1B873593    # peer draw: stake-class uniform
SALT_PULL_MEMBER = 0xE6546B64   # peer draw: within-class uniform
SALT_PULL_BLOOM = 0xCC9E2D51    # per-(round, requester) bloom-FP event
SALT_PULL_LOSS = 0x38B34AE5     # per-(round, requester, peer) request loss

# per-slot outcome codes (trace schema v2; obs/trace.py ``pull_code``)
PULL_EMPTY = 0            # inactive slot / self-draw / dead requester
PULL_RESPONSE = 1         # value transferred to the requester
PULL_PEER_FAILED = 2      # request sent into a churn-failed peer
PULL_SUPPRESSED = 3       # cross-partition request suppressed
PULL_DROPPED = 4          # request lost to packet loss
PULL_MISS_NOT_HELD = 5    # peer does not hold the value this round
PULL_MISS_ALREADY_HELD = 6  # requester already holds it (bloom true match)
PULL_MISS_BLOOM_FP = 7    # bloom false positive filtered the rescue out
PULL_MISS_CAPPED = 8      # peer's pull_request_cap already exhausted
PULL_CODE_NAMES = {
    PULL_EMPTY: "empty",
    PULL_RESPONSE: "response",
    PULL_PEER_FAILED: "peer_failed",
    PULL_SUPPRESSED: "suppressed",
    PULL_DROPPED: "dropped",
    PULL_MISS_NOT_HELD: "miss_not_held",
    PULL_MISS_ALREADY_HELD: "miss_already_held",
    PULL_MISS_BLOOM_FP: "miss_bloom_fp",
    PULL_MISS_CAPPED: "miss_capped",
}


def u01_from_u32(h: int) -> np.float32:
    """u32 hash -> f32 uniform in [0, 1): ``(h >> 8) * 2^-24``.

    The 24 surviving bits fit the f32 mantissa exactly, so the value is
    identical whether computed on Python ints (here) or uint32 lanes
    (engine/core.py ``_pull_u01``)."""
    return np.float32(h >> 8) * np.float32(2.0 ** -24)


class PullTables(NamedTuple):
    """Static stake-class sampling tables for the pull peer draw (numpy).

    ``cdf`` is the top-entry (k = 24) class CDF — weights ``(bucket+1)^2``
    — computed with the identical f64-cumsum -> f32 arithmetic as
    ``engine/sampler.build_sampler_tables``, so ``cdf`` equals the engine's
    ``sampler.class_cdf[-1]`` bit-for-bit (tests/test_pull.py locks this
    down)."""

    perm: np.ndarray         # [N] i32  node ids sorted by bucket (stable)
    class_start: np.ndarray  # [NB] i32
    class_count: np.ndarray  # [NB] i32
    cdf: np.ndarray          # [NB] f32 inclusive CDF, cdf[-1] == 1.0


def pull_class_tables(stakes) -> PullTables:
    """Build the pull sampling tables from the per-node stake vector."""
    buckets = stake_buckets_array(
        np.asarray(stakes, dtype=np.int64).astype(np.uint64)).astype(np.int32)
    class_count = np.bincount(buckets, minlength=NB).astype(np.int32)
    class_start = np.concatenate(
        [[0], np.cumsum(class_count)[:-1]]).astype(np.int32)
    c = np.arange(NB)
    mass = class_count.astype(np.float64) * ((c + 1) ** 2)
    cdf = np.cumsum(mass)
    total = cdf[-1] if cdf[-1] != 0 else 1.0
    cdf = (cdf / total).astype(np.float32)
    cdf[-1] = 1.0
    return PullTables(
        perm=np.argsort(buckets, kind="stable").astype(np.int32),
        class_start=class_start,
        class_count=class_count,
        cdf=cdf,
    )


def sample_pull_peer(tables: PullTables, basis_cls: int, basis_mem: int,
                     node: int, slot: int) -> int:
    """One stake-weighted pull peer draw (scalar path; may return ``node``
    itself — self-draws discard the slot).

    Mirrors the engine's elementwise draw exactly: f32 class compare
    against the shared CDF, f32 ``floor(u * count)`` within the class."""
    u_cls = u01_from_u32(edge_u32(basis_cls, node, slot))
    cls = int(np.count_nonzero(u_cls >= tables.cdf[:-1]))
    start = int(tables.class_start[cls])
    count = int(tables.class_count[cls])
    u_mem = u01_from_u32(edge_u32(basis_mem, node, slot))
    pos = start + int(np.floor(u_mem * np.float32(count)))
    pos = min(pos, start + max(count - 1, 0))
    return int(tables.perm[pos])


class PullRound(NamedTuple):
    """One round's pull-phase outcome (oracle side; the engine emits the
    same quantities as ``rows["pull_*"]``)."""

    requests: int            # requests that arrived at a live peer
    responses: int           # value transfers
    misses: int              # arrived requests that transferred nothing
    dropped: int             # loss-dropped requests
    suppressed: int          # partition-suppressed requests
    rescued: dict            # {node index: pull hop} — push-unreached nodes
                             # delivered via pull this round
    egress: np.ndarray       # [N] i64 per-node pull egress (req out + resp out)
    ingress: np.ndarray      # [N] i64 per-node pull ingress (req in + resp in)
    peers: np.ndarray        # [N, PS] i16 sampled peer per slot (-1 inactive)
    code: np.ndarray         # [N, PS] i8 PULL_* outcome per slot
    pull_hop: np.ndarray     # [N] i16 pull delivery hop (-1 none)


class PullOracle:
    """CPU-oracle pull phase: the identical spec as the engine's
    ``round/pull`` block (engine/core.py), implemented as plain per-node /
    per-slot loops over the scalar counter hashes — an independent
    formulation the 1k-node parity test (tests/test_pull.py) checks the
    sort-routed engine against bit-for-bit."""

    def __init__(self, stakes, *, seed: int = 0, pull_fanout: int = 2,
                 pull_interval: int = 1, pull_bloom_fp_rate: float = 0.1,
                 pull_request_cap: int = 0, pull_slots: int = 0,
                 packet_loss_rate: float = 0.0, partition_at: int = -1,
                 heal_at: int = -1):
        stakes = np.asarray(stakes, dtype=np.int64)
        self.n = int(stakes.shape[0])
        self.tables = pull_class_tables(stakes)
        self.seed = int(seed)
        self.pull_fanout = int(pull_fanout)
        self.pull_interval = max(1, int(pull_interval))
        self.fp_thr = rate_threshold(pull_bloom_fp_rate)
        self.cap = int(pull_request_cap)
        self.pull_slots = int(pull_slots) if pull_slots > 0 else max(
            8, self.pull_fanout)
        self.loss_thr = rate_threshold(packet_loss_rate)
        self.partition_at = int(partition_at)
        self.heal_at = int(heal_at)
        self.side = (stake_bipartition(stakes)
                     if self.partition_at >= 0 else None)

    def pull_round_active(self, it: int) -> bool:
        return it % self.pull_interval == 0

    def run_round(self, it: int, hops, failed) -> PullRound:
        """Run one pull exchange against this round's push outcome.

        ``hops``: [N] int, the push BFS hop distance per node (-1 =
        unreached; the origin is 0).  ``failed``: [N] bool, the node-failure
        mask in effect this round (post-churn).  Responses are based on the
        push-reached state only — one request/response exchange per pull
        round, no intra-round cascade."""
        n, ps = self.n, self.pull_slots
        hops = np.asarray(hops)
        failed = np.asarray(failed, dtype=bool)
        peers = np.full((n, ps), -1, np.int16)
        code = np.zeros((n, ps), np.int8)
        pull_hop = np.full(n, -1, np.int16)
        egress = np.zeros(n, np.int64)
        ingress = np.zeros(n, np.int64)
        res = PullRound(0, 0, 0, 0, 0, {}, egress, ingress, peers, code,
                        pull_hop)
        if not self.pull_round_active(it):
            return res
        requests = responses = misses = dropped = suppressed = 0
        rescued = {}
        b_cls = round_basis(self.seed, it, SALT_PULL_CLASS)
        b_mem = round_basis(self.seed, it, SALT_PULL_MEMBER)
        b_fp = round_basis(self.seed, it, SALT_PULL_BLOOM)
        b_loss = round_basis(self.seed, it, SALT_PULL_LOSS)
        part_on = (self.side is not None
                   and partition_active(it, self.partition_at, self.heal_at))
        served = np.zeros(n, np.int64)   # requests answered per peer
        for r in range(n):
            if failed[r]:
                continue
            holds_r = hops[r] >= 0
            fp_r = (self.fp_thr
                    and node_u32(b_fp, r) < self.fp_thr)
            best = -1
            for s in range(min(self.pull_fanout, ps)):
                peer = sample_pull_peer(self.tables, b_cls, b_mem, r, s)
                if peer == r:
                    continue   # self-draw: slot discarded
                peers[r, s] = peer
                if failed[peer]:
                    code[r, s] = PULL_PEER_FAILED
                    continue
                if part_on and self.side[r] != self.side[peer]:
                    code[r, s] = PULL_SUPPRESSED
                    suppressed += 1
                    continue
                if (self.loss_thr
                        and edge_u32(b_loss, r, peer) < self.loss_thr):
                    code[r, s] = PULL_DROPPED
                    dropped += 1
                    continue
                # arrived: requester egress + peer ingress
                requests += 1
                egress[r] += 1
                ingress[peer] += 1
                if self.cap > 0 and served[peer] >= self.cap:
                    code[r, s] = PULL_MISS_CAPPED
                    misses += 1
                    continue
                served[peer] += 1
                if hops[peer] < 0:
                    code[r, s] = PULL_MISS_NOT_HELD
                    misses += 1
                elif holds_r:
                    code[r, s] = PULL_MISS_ALREADY_HELD
                    misses += 1
                elif fp_r:
                    code[r, s] = PULL_MISS_BLOOM_FP
                    misses += 1
                else:
                    code[r, s] = PULL_RESPONSE
                    responses += 1
                    egress[peer] += 1
                    ingress[r] += 1
                    h = int(hops[peer]) + 1
                    best = h if best < 0 else min(best, h)
            if best >= 0:
                rescued[r] = best
                pull_hop[r] = best
        return PullRound(requests, responses, misses, dropped, suppressed,
                         rescued, egress, ingress, peers, code, pull_hop)
