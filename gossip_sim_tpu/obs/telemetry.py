"""Live telemetry plane: the hub + the structured event log (ISSUE 18).

Every observability surface before this one — spans/run-reports, traces,
capacity, node health — is *post-hoc*: harvested once, at exit.  The hub
turns those same registries into a surface that is inspectable **while
the run is alive**:

* :class:`TelemetryHub` composes one consistent point-in-time snapshot
  from the span registry (timers/counters/info), heartbeat progress +
  ETA, the memwatch RSS series and peaks, the capacity ledger, health
  digests, live Influx sender stats (via a provider callback registered
  by the CLI once the sender thread exists), and the resilience journal
  commit counters.  `obs/exporter.py` serves this snapshot over HTTP.
* The **structured event log** (``--event-log``, schema
  ``gossip-sim-tpu/events/v1``, JSONL) unifies the scattered free-text
  signals — heartbeat ticks, watchdog retries/CPU-fallbacks, journal
  commits, SIGTERM/SIGINT, Influx retry/spool, sweep/lane/batch
  boundaries — into versioned records.  Each record carries the run-key
  fingerprint and (where applicable) the unit id, so events join 1:1
  against the resilience journal's committed units.

Contracts (the standing observability discipline):

* **JAX-free** — importing this module never touches an accelerator.
* **never kills a run** — emit/snapshot failures are swallowed; a
  telemetry bug must not take down a multi-hour sweep.
* **zero bit-impact** — the hub only *reads* simulation state; it never
  feeds the stats layer or the deterministic Influx wire surface.
* **reentrant-safe** — the hub lock is an RLock because events can be
  emitted from signal handlers interrupting an in-progress emit.

One process == one run: :func:`reset` joins the registry/memwatch/
capacity reset block at the top of ``cli.main``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import deque

from .spans import get_registry

log = logging.getLogger("gossip_sim_tpu.obs")

#: schema tag carried by every event record (JSONL event log + /events)
EVENT_SCHEMA = "gossip-sim-tpu/events/v1"

#: v2 extends v1 with the serve lifecycle (ISSUE 20); only records whose
#: event type is serve-specific carry the v2 tag, so a non-serve run
#: still writes a pure v1 log and every v1 consumer keeps validating
EVENT_SCHEMA_V2 = "gossip-sim-tpu/events/v2"

#: schema tag carried by every hub snapshot (/metrics + tests)
TELEMETRY_SCHEMA = "gossip-sim-tpu/telemetry/v1"

#: event types the v1 schema admits (validation is a closed-world check
#: so a typo'd emit site fails the smoke gate instead of shipping junk)
EVENT_TYPES = frozenset({
    "run_start",          # process entered a run path (argv, pid)
    "run_end",            # run finished (rc)
    "telemetry_listen",   # exporter bound its port (port)
    "heartbeat",          # a logged progress tick (done/total/rate/eta_s)
    "unit_done",          # sweep/lane/batch boundary (unit)
    "journal_commit",     # a unit durably committed (unit)
    "journal_resume",     # a prior journal replayed (units)
    "shutdown_signal",    # SIGTERM/SIGINT observed (signum)
    "resumable_exit",     # run exiting with the resumable code
    "device_retry",       # watchdog retrying a failed dispatch (attempt)
    "device_fallback",    # watchdog re-executing a unit on CPU
    "influx_retry",       # sender POST retry (attempt)
    "influx_spool",       # sender spooled points to disk (points)
    "influx_drop",        # sender dropped points (points)
})

#: serve lifecycle events introduced by the v2 registry (gossip-as-a-
#: service daemon, serve/).  Kept separate from the v1 set so the v1
#: closed-world check stays exactly as strict as it shipped.
SERVE_EVENT_TYPES = frozenset({
    "request_received",   # intake accepted a request spec (request, tenant)
    "request_admitted",   # scheduler spliced it into a lane (lane)
    "request_rejected",   # admission refused it (reason, predicted_bytes)
    "request_completed",  # lane retired; result + report durable
    "lane_evicted",       # lane freed (retire/drain) and re-admittable
})

#: event types the v2 schema admits (superset of v1)
EVENT_TYPES_V2 = EVENT_TYPES | SERVE_EVENT_TYPES

#: ring-buffer depth backing /events (independent of file logging)
RING_DEPTH = 1024


def run_key_fingerprint(run_key: dict) -> str:
    """Stable 16-hex digest of a resilience run key (canonical JSON).

    Recomputable from a journal header's ``run_key`` dict, so event-log
    records and journal units join on ``(fingerprint, unit)`` without
    the consumer needing the full key in every record.
    """
    blob = json.dumps(dict(run_key or {}), sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class TelemetryHub:
    """Thread-safe composition point for the live telemetry plane."""

    def __init__(self):
        # RLock: emit() can be re-entered by a signal handler that fires
        # while the main thread is inside emit()/snapshot()
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=RING_DEPTH)
        self._seq = 0
        self._dropped_events = 0
        self._event_fh = None
        self._event_path = ""
        self._run_fp = ""
        self._progress: dict[str, dict] = {}   # label -> latest state
        self._providers: dict[str, object] = {}  # name -> () -> dict
        self._t0 = time.time()

    # -- identity ---------------------------------------------------------

    def set_run_key(self, run_key: dict) -> str:
        """Stamp the run-key fingerprint carried by subsequent events."""
        fp = run_key_fingerprint(run_key)
        with self._lock:
            self._run_fp = fp
        return fp

    def run_fingerprint(self) -> str:
        with self._lock:
            return self._run_fp

    # -- event log --------------------------------------------------------

    def open_event_log(self, path: str) -> None:
        """Open (append) the JSONL event log.  Append mode is load-bearing:
        an interrupted-and-resumed run reuses the same path, and the
        resumed process must extend the record, not erase it."""
        with self._lock:
            self.close_event_log()
            self._event_fh = open(path, "a", encoding="utf-8")
            self._event_path = path

    def close_event_log(self) -> None:
        with self._lock:
            if self._event_fh is not None:
                try:
                    self._event_fh.close()
                except OSError:  # pragma: no cover - best-effort close
                    pass
                self._event_fh = None

    @property
    def event_log_path(self) -> str:
        with self._lock:
            return self._event_path

    def emit(self, event_type: str, unit: int | None = None,
             run: str | None = None, **fields) -> dict | None:
        """Append one structured event (ring buffer + optional JSONL).

        Never raises: a full disk or closed handle must not kill the
        run — failed file writes are counted, the ring still advances.
        """
        try:
            with self._lock:
                self._seq += 1
                schema = (EVENT_SCHEMA_V2
                          if event_type in SERVE_EVENT_TYPES
                          else EVENT_SCHEMA)
                rec = {"schema": schema, "seq": self._seq,
                       "ts": round(time.time(), 6), "ev": str(event_type),
                       "run": self._run_fp if run is None else str(run)}
                if unit is not None:
                    rec["unit"] = int(unit)
                for k, v in fields.items():
                    if v is not None:
                        rec[k] = v
                self._ring.append(rec)
                if self._event_fh is not None:
                    try:
                        self._event_fh.write(
                            json.dumps(rec, separators=(",", ":"),
                                       default=str) + "\n")
                        self._event_fh.flush()
                    except (OSError, ValueError):
                        self._dropped_events += 1
                return rec
        except Exception:  # pragma: no cover - emit must never kill a run
            return None

    def recent_events(self, n: int = 100) -> list:
        """Most recent ``n`` events (oldest first) from the ring buffer."""
        with self._lock:
            n = max(0, int(n))
            ring = list(self._ring)
        return ring[-n:] if n else []

    def events_emitted(self) -> int:
        with self._lock:
            return self._seq

    # -- live progress (heartbeats) ---------------------------------------

    def note_progress(self, label: str, state: dict) -> None:
        """Record a heartbeat's latest structured state (every beat()
        call, including log-suppressed ones, keeps this fresh)."""
        with self._lock:
            self._progress[str(label)] = dict(state)

    # -- live providers (Influx sender, ...) ------------------------------

    def set_provider(self, name: str, fn) -> None:
        """Register a callable returning a JSON-safe dict, polled at
        snapshot time (e.g. the Influx sender's live stats)."""
        with self._lock:
            if fn is None:
                self._providers.pop(name, None)
            else:
                self._providers[name] = fn

    # -- the composed snapshot --------------------------------------------

    def snapshot(self) -> dict:
        """One consistent point-in-time view of the whole run.

        The span registry's snapshot is atomic under its own lock (no
        torn span [total_s, count] pairs); hub-owned state is copied
        under the hub lock; providers are polled outside both locks so a
        slow sender can't block emitters.
        """
        reg = get_registry()
        snap = reg.snapshot()
        info = snap["info"]
        with self._lock:
            progress = {k: dict(v) for k, v in self._progress.items()}
            providers = dict(self._providers)
            events = {"emitted": self._seq,
                      "dropped_writes": self._dropped_events,
                      "log": self._event_path,
                      "buffered": len(self._ring)}
            run_fp = self._run_fp
            t0 = self._t0
        polled = {}
        for name, fn in providers.items():
            try:
                polled[name] = dict(fn())
            except Exception:  # pragma: no cover - provider must not kill
                polled[name] = {}
        counters = snap["counters"]
        out = {
            "schema": TELEMETRY_SCHEMA,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "run": {
                "fingerprint": run_fp,
                "platform": str(info.get("platform", "unknown")),
                "num_nodes": int(info.get("num_nodes", 0) or 0),
                "run_path": str(info.get("run_path", "")),
                "started_unix": round(t0, 3),
                "wall_s": round(snap["wall_s"], 3),
            },
            "spans": snap["spans"],
            "counters": counters,
            "progress": progress,
            "engine": {
                "compiles": int(counters.get("engine/compiles", 0)),
                "cache_hits": int(counters.get("engine/cache_hits", 0)),
            },
            "resilience": {
                "committed_units":
                    int(counters.get("resilience/committed_units", 0)),
                "resumed_units":
                    int(counters.get("resilience/resumed_units", 0)),
                "device_failures":
                    int(counters.get("resilience/device_failures", 0)),
                "fallback_units":
                    int(counters.get("resilience/fallback_units", 0)),
            },
            "capacity": _capacity_view(info),
            "health": _health_view(info),
            "memwatch": _memwatch_view(),
            "influx": polled.get("influx", {}),
            "serve": polled.get("serve", {}),
            "events": events,
        }
        return out

    # -- lifecycle --------------------------------------------------------

    def reset(self) -> None:
        """One process == one run: drop ring/progress/providers and close
        any event log a previous in-process run left open."""
        with self._lock:
            self.close_event_log()
            self._ring.clear()
            self._seq = 0
            self._dropped_events = 0
            self._event_path = ""
            self._run_fp = ""
            self._progress.clear()
            self._providers.clear()
            self._t0 = time.time()


def _capacity_view(info: dict) -> dict:
    led = dict(info.get("capacity_ledger") or {})
    return {
        "ledger_total_bytes": int(led.get("total_bytes", 0) or 0),
        "ledger_bytes_per_node": float(led.get("bytes_per_node", 0) or 0),
    }


def _health_view(info: dict) -> dict:
    nh = info.get("node_health") or {}
    return {"enabled": bool(nh.get("enabled", False))}


def _memwatch_view() -> dict:
    try:
        from . import memwatch
        mw = memwatch.snapshot()
        return {
            "rss_bytes": int(mw.get("last_rss_bytes", 0)),
            "peak_rss_bytes": int(mw.get("peak_rss_bytes", 0)),
            "peak_device_bytes": int(mw.get("peak_device_bytes", 0)),
            "samples": int(mw.get("samples", 0)),
        }
    except Exception:  # pragma: no cover - snapshot must never fail
        return {"rss_bytes": 0, "peak_rss_bytes": 0,
                "peak_device_bytes": 0, "samples": 0}


# -- event validation (the v1 schema contract) ----------------------------

#: required fields and accepted types for every v1 event record
_EVENT_REQUIRED = {
    "schema": str,
    "seq": int,
    "ts": (int, float),
    "ev": str,
    "run": str,
}


def validate_event(rec) -> list:
    """Schema check for one event record: list of problems (empty=ok)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"event is {type(rec).__name__}, not dict"]
    for key, types in _EVENT_REQUIRED.items():
        if key not in rec:
            problems.append(f"missing key: {key}")
        elif not isinstance(rec[key], types):
            problems.append(f"key {key}: expected {types}, got "
                            f"{type(rec[key]).__name__}")
    schema = rec.get("schema")
    if schema not in (EVENT_SCHEMA, EVENT_SCHEMA_V2):
        problems.append(f"unknown schema: {schema!r}")
    # closed-world type check per schema generation: a v1 record must
    # carry a v1 type (serve events tagged v1 are a bug, not forward
    # compatibility), a v2 record anything the v2 registry admits
    admitted = EVENT_TYPES if schema == EVENT_SCHEMA else EVENT_TYPES_V2
    if "ev" in rec and rec["ev"] not in admitted:
        problems.append(f"unknown event type: {rec['ev']!r}")
    if "unit" in rec and not isinstance(rec["unit"], int):
        problems.append("unit must be int")
    if "seq" in rec and isinstance(rec["seq"], int) and rec["seq"] < 1:
        problems.append("seq must be >= 1")
    return problems


def validate_event_log(path: str) -> list:
    """Validate a JSONL event log file: every line parses, every record
    passes :func:`validate_event`, and seq is strictly increasing within
    each process run (seq restarts at 1 when a resumed process appends
    to the same file — detected by a seq drop back to 1)."""
    problems = []
    last_seq = 0
    try:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    problems.append(f"line {i}: unparseable JSON ({e})")
                    continue
                for p in validate_event(rec):
                    problems.append(f"line {i}: {p}")
                seq = rec.get("seq")
                if isinstance(seq, int):
                    if seq != 1 and seq <= last_seq:
                        problems.append(
                            f"line {i}: seq {seq} not increasing "
                            f"(prev {last_seq})")
                    last_seq = seq
    except OSError as e:
        problems.append(f"unreadable: {e}")
    return problems


def load_event_log(path: str) -> list:
    """All parseable records of a JSONL event log, in file order."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


# -- module singleton (one process == one run) ----------------------------

_HUB = TelemetryHub()


def get_hub() -> TelemetryHub:
    """The process-wide hub (one process == one run)."""
    return _HUB


def emit_event(event_type: str, unit: int | None = None,
               run: str | None = None, **fields) -> dict | None:
    """``telemetry.emit_event("journal_commit", unit=3)`` on the hub."""
    return _HUB.emit(event_type, unit=unit, run=run, **fields)


def reset() -> None:
    """Reset the shared hub (joins cli.main's per-run reset block)."""
    _HUB.reset()
