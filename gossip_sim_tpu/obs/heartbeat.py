"""Heartbeat logging with rate + ETA for long sweeps and batch loops.

A sweep over hundreds of origins (or a thousand measured rounds) can run
for minutes with no output between Influx drains; the heartbeat gives the
operator a cheap periodic "N/M done, X/s, ETA H:MM:SS" line without any
per-unit logging cost — ``beat()`` is a monotonic-clock compare unless the
interval elapsed.
"""

from __future__ import annotations

import logging
import time

log = logging.getLogger("gossip_sim_tpu.obs")


def _fmt_hms(seconds: float) -> str:
    seconds = max(0, int(seconds))
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    return f"{h}:{m:02d}:{s:02d}"


class Heartbeat:
    """Rate/ETA logger: ``beat(done)`` logs at most every ``interval_s``."""

    def __init__(self, total_units: int, label: str = "progress",
                 unit: str = "unit", interval_s: float = 30.0,
                 logger: logging.Logger | None = None):
        self.total = max(int(total_units), 0)
        self.label = label
        self.unit = unit
        self.interval_s = interval_s
        self.beats_logged = 0
        # resumability marker (resilience.py): units durably committed to
        # the run journal; None = this loop does not journal
        self.committed = None
        self._log = logger if logger is not None else log
        self._t0 = time.monotonic()
        self._last = self._t0

    def note_committed(self, committed_units: int) -> None:
        """Record journal progress; subsequent beats carry a
        "committed i/K, resumable" marker so an operator watching the log
        knows exactly how much a preemption would preserve."""
        self.committed = max(0, int(committed_units))

    def _format(self, done: int, now: float) -> str:
        # Hardened for the degenerate ticks (ISSUE 3): done < 0 or beyond
        # total is clamped; zero completed steps (or a zero-elapsed first
        # tick) reports rate 0 and ETA "?" instead of dividing by zero; a
        # finished loop always reports ETA 0:00:00 even when the rate is
        # unmeasurable (the single-step case: total=1, first beat is the
        # last).  ETA never goes negative.
        done = max(0, min(done, self.total) if self.total else done)
        elapsed = max(0.0, now - self._t0)
        pct = 100.0 * done / self.total if self.total else 0.0
        rate = done / elapsed if elapsed > 0 else 0.0
        if self.total and done >= self.total:
            eta = _fmt_hms(0)
        elif rate > 0 and self.total:
            eta = _fmt_hms(max(0.0, (self.total - done) / rate))
        else:
            eta = "?"
        marker = ("" if self.committed is None else
                  f" | committed {min(self.committed, self.total) if self.total else self.committed}"
                  f"/{self.total}, resumable")
        return (f"HEARTBEAT {self.label}: {done}/{self.total} {self.unit}s "
                f"({pct:.1f}%) | {rate:.2f} {self.unit}/s | "
                f"elapsed {_fmt_hms(elapsed)} | ETA {eta}{marker}")

    def beat(self, done_units: int, force: bool = False) -> str | None:
        """Log progress if ``interval_s`` elapsed since the last beat (or
        ``force``).  Returns the logged message, or None if suppressed."""
        now = time.monotonic()
        if not force and now - self._last < self.interval_s:
            return None
        msg = self._format(done_units, now)
        self._log.info("%s", msg)
        self._last = now
        self.beats_logged += 1
        return msg

    def finish(self) -> str:
        """Unconditional final beat at 100%% (end-of-loop summary)."""
        return self.beat(self.total, force=True)
