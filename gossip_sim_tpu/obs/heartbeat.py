"""Heartbeat logging with rate + ETA for long sweeps and batch loops.

A sweep over hundreds of origins (or a thousand measured rounds) can run
for minutes with no output between Influx drains; the heartbeat gives the
operator a cheap periodic "N/M done, X/s, ETA H:MM:SS" line without any
per-unit logging cost — ``beat()`` is a monotonic-clock compare unless the
interval elapsed.

Live telemetry (ISSUE 18): every ``beat()`` call — including the
log-suppressed ones — publishes its structured :meth:`state` to the
telemetry hub, so ``/metrics`` and ``/status`` always carry fresh
progress at unit granularity; every *logged* tick is additionally
emitted as a structured ``heartbeat`` event (machine-readable progress
for daemonized/redirected runs), with the same zero-step/overshoot ETA
hardening in the payload as in the log line.
"""

from __future__ import annotations

import logging
import time

from . import telemetry

log = logging.getLogger("gossip_sim_tpu.obs")


def _fmt_hms(seconds: float) -> str:
    seconds = max(0, int(seconds))
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    return f"{h}:{m:02d}:{s:02d}"


class Heartbeat:
    """Rate/ETA logger: ``beat(done)`` logs at most every ``interval_s``."""

    def __init__(self, total_units: int, label: str = "progress",
                 unit: str = "unit", interval_s: float = 30.0,
                 logger: logging.Logger | None = None):
        self.total = max(int(total_units), 0)
        self.label = label
        self.unit = unit
        self.interval_s = interval_s
        self.beats_logged = 0
        # resumability marker (resilience.py): units durably committed to
        # the run journal; None = this loop does not journal
        self.committed = None
        self._log = logger if logger is not None else log
        self._t0 = time.monotonic()
        self._last = self._t0

    def note_committed(self, committed_units: int) -> None:
        """Record journal progress; subsequent beats carry a
        "committed i/K, resumable" marker so an operator watching the log
        knows exactly how much a preemption would preserve."""
        self.committed = max(0, int(committed_units))

    def state(self, done: int, now: float | None = None) -> dict:
        """Structured progress payload (the event/hub counterpart of the
        log line), hardened for the same degenerate ticks as
        :meth:`_format`: ``done`` is clamped into [0, total] (the raw
        value survives as ``raw_done`` so an overshooting caller is
        visible, not hidden); zero completed steps or a zero-elapsed
        first tick report rate 0 and ``eta_s: None`` (the log's "?");
        a finished loop reports ``eta_s: 0`` even when the rate is
        unmeasurable."""
        if now is None:
            now = time.monotonic()
        raw = int(done)
        done = max(0, min(raw, self.total) if self.total else raw)
        elapsed = max(0.0, now - self._t0)
        pct = 100.0 * done / self.total if self.total else 0.0
        rate = done / elapsed if elapsed > 0 else 0.0
        if self.total and done >= self.total:
            eta_s = 0.0
        elif rate > 0 and self.total:
            eta_s = round(max(0.0, (self.total - done) / rate), 3)
        else:
            eta_s = None
        return {
            "label": self.label,
            "unit": self.unit,
            "done": done,
            "raw_done": raw,
            "total": self.total,
            "pct": round(pct, 3),
            "rate_per_s": round(rate, 4),
            "elapsed_s": round(elapsed, 3),
            "eta_s": eta_s,
            "committed": self.committed,
        }

    def _format(self, done: int, now: float) -> str:
        # Hardened for the degenerate ticks (ISSUE 3): done < 0 or beyond
        # total is clamped; zero completed steps (or a zero-elapsed first
        # tick) reports rate 0 and ETA "?" instead of dividing by zero; a
        # finished loop always reports ETA 0:00:00 even when the rate is
        # unmeasurable (the single-step case: total=1, first beat is the
        # last).  ETA never goes negative.
        done = max(0, min(done, self.total) if self.total else done)
        elapsed = max(0.0, now - self._t0)
        pct = 100.0 * done / self.total if self.total else 0.0
        rate = done / elapsed if elapsed > 0 else 0.0
        if self.total and done >= self.total:
            eta = _fmt_hms(0)
        elif rate > 0 and self.total:
            eta = _fmt_hms(max(0.0, (self.total - done) / rate))
        else:
            eta = "?"
        marker = ("" if self.committed is None else
                  f" | committed {min(self.committed, self.total) if self.total else self.committed}"
                  f"/{self.total}, resumable")
        return (f"HEARTBEAT {self.label}: {done}/{self.total} {self.unit}s "
                f"({pct:.1f}%) | {rate:.2f} {self.unit}/s | "
                f"elapsed {_fmt_hms(elapsed)} | ETA {eta}{marker}")

    def beat(self, done_units: int, force: bool = False) -> str | None:
        """Log progress if ``interval_s`` elapsed since the last beat (or
        ``force``).  Returns the logged message, or None if suppressed.

        Every call (suppressed or not) refreshes the telemetry hub's
        progress slot for this label; logged ticks also emit a
        ``heartbeat`` structured event.
        """
        now = time.monotonic()
        state = self.state(done_units, now)
        telemetry.get_hub().note_progress(self.label, state)
        if not force and now - self._last < self.interval_s:
            return None
        msg = self._format(done_units, now)
        self._log.info("%s", msg)
        self._last = now
        self.beats_logged += 1
        # "unit" in an event record is the journal unit id (an int);
        # the heartbeat's unit *name* travels as unit_name
        payload = dict(state)
        payload["unit_name"] = payload.pop("unit")
        telemetry.emit_event("heartbeat", **payload)
        return msg

    def finish(self) -> str:
        """Unconditional final beat at 100%% (end-of-loop summary)."""
        return self.beat(self.total, force=True)
