"""Machine-readable run reports (``--run-report out.json``).

One schema serves both the product CLI and bench.py so BENCH trajectory
files and production runs are directly comparable: the report's top level
carries exactly the flat keys bench.py has always emitted (``metric``,
``value``, ``unit``, ``vs_baseline``, ``platform``, ``num_nodes``,
``origin_batch``, ``iterations``, ``elapsed_s``, ``init_s``,
``compile_s``, ``coverage_mean``, ``rmr_mean``) sourced from the shared
span registry, plus nested sections the bench's one-liner omits:

* ``config``       — the full simulation Config, JSON-safe
* ``environment``  — python/jax versions, platform, device count, mesh
* ``spans``        — every recorded span: ``{name: {total_s, count}}``
* ``counters``     — raw counters (origin-iters, messages, engine/compiles,
                     engine/cache_hits, padded_sims, ...)
* ``throughput``   — origin-iters/s (steady), messages/s, end-to-end wall
* ``faults``       — delivered/dropped/suppressed totals when impaired
* ``influx``       — points sent / dropped / retries / final queue depth
* ``compilation_cache`` — persistent XLA cache dir + hit/miss counts
                     (engine/cache.py; all-zero when never enabled)
* ``capacity``     — the capacity observatory (obs/capacity.py,
                     obs/memwatch.py; ISSUE 13): the closed-form memory
                     ``ledger`` the run path stamped into registry info,
                     the XLA ``cost`` harvest summary (FLOPs,
                     argument/output/temp/generated-code bytes, keyed by
                     compile-cache entry), and the ``memwatch`` footprint
                     snapshot (peak/series RSS, device bytes-in-use).
                     The memwatch peak is always nonzero (kernel VmHWM);
                     ledger/cost fill in when a run path computed them /
                     ``--capacity-harvest`` was on

Compile-accounting counters (engine/core.py run_rounds; ISSUE 4):

* ``engine/compiles``   — jitted round-scan executables built this run.
                          A K-step sweep over any numeric EngineKnobs
                          field reads exactly 1 here; shape/structure
                          steps (fanout, active-set size) add one per
                          distinct EngineStatic value.
* ``engine/cache_hits`` — engine calls served by an already-compiled
                          executable (sweep steps 2..K, later blocks).
Both surface as flat top-level keys (``compiles``/``cache_hits``) so
BENCH lines capture amortization, not just raw speed.

Lane-mode sweeps (``--sweep-lanes``, ISSUE 6) additionally surface
``sweep_lanes`` (lanes per batched engine call; 0 = serial sweep) and
``lane_batches`` (batched calls the sweep took, ceil(K/lanes)) as flat
top-level keys from registry info; a whole lane-mode sweep reads
``compiles == 1`` with ``lane_batches - 1`` cache hits.

Span-name conventions (shared by cli.py, bench.py, tools/):

* ``ingest``          account source -> {pubkey: stake}
* ``engine/tables``   make_cluster_tables
* ``engine/init``     init_state (first device allocation).  In the
                      double-buffered --all-origins loop this times the
                      host-side dispatch only — device init overlaps the
                      previous batch's harvest, so all-origins init_s is
                      smaller than a serialized run's
* ``engine/compile``  the run's FIRST jitted rounds call (compile-
                      dominated; the warm-up scan in the CLI, the timing
                      warm-up in bench.py — same semantic as the
                      historical ``compile_s``).  Recorded at most once
                      per run: later warm-cache calls land in
                      engine/warmup or engine/rounds
* ``engine/warmup``   warm-up scans after the compile carrier (sims 2..N
                      of a sweep re-running against the jit cache)
* ``engine/rounds``   steady-state measured round blocks; the ONLY span
                      feeding the throughput denominators
* ``stats/harvest``   device->host transfer + stats-layer feeding
* ``checkpoint/save`` checkpoint writes
* ``influx/drain``    end-of-run reporter-thread drain
"""

from __future__ import annotations

import dataclasses
import enum
import json
import sys

RUN_REPORT_SCHEMA = "gossip-sim-tpu/run-report/v1"

# North-star per-chip throughput share (BASELINE.md): 10k nodes x all
# origins x 1000 iters < 60 s on a v5e-8 == 166,667 origin-iters/s / 8.
PER_CHIP_TARGET = 166_667.0 / 8

#: top-level keys every report must carry, with accepted types
REQUIRED_KEYS = {
    "schema": str,
    "metric": str,
    "value": (int, float),
    "unit": str,
    "vs_baseline": (int, float),
    "platform": str,
    "num_nodes": int,
    "origin_batch": int,
    "iterations": int,
    "elapsed_s": (int, float),
    "init_s": (int, float),
    "compile_s": (int, float),
    "compiles": int,
    "cache_hits": int,
    "config": dict,
    "environment": dict,
    "spans": dict,
    "counters": dict,
    "throughput": dict,
    "faults": dict,
    "influx": dict,
    "stats": dict,
    "compilation_cache": dict,
    "resilience": dict,
    "capacity": dict,
    "node_health": dict,
    "telemetry": dict,
    "serve": dict,
}


def _jsonable(value):
    """Best-effort JSON-safe conversion (enums/StepSize -> str)."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, enum.Enum):
        return str(value)
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set)):
        return [_jsonable(v) for v in value]
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: _jsonable(getattr(value, f.name))
                for f in dataclasses.fields(value)}
    return str(value)


def config_dict(config) -> dict:
    """A Config dataclass as a JSON-safe dict."""
    return _jsonable(config)


def environment_info(platform: str = "", mesh_shape=None) -> dict:
    """Python/JAX versions + device inventory.  JAX is imported lazily so
    report assembly never forces accelerator init on its own; callers that
    already initialized a backend pass ``platform`` through."""
    env = {
        "python": sys.version.split()[0],
        "jax_version": None,
        "platform": platform or "unknown",
        "device_count": None,
        "mesh_shape": list(mesh_shape) if mesh_shape else None,
    }
    backend_up = "jax" in sys.modules
    try:
        import jax
        env["jax_version"] = jax.__version__
        if backend_up and platform:
            # backend already up (the caller measured on it): count is safe
            env["device_count"] = len(jax.devices())
    except Exception:  # pragma: no cover - report must never kill a run
        pass
    return env


def _flat_summary(registry, *, platform: str, num_nodes: int,
                  origin_batch: int, iterations: int) -> dict:
    """The bench-compatible flat keys, sourced from the shared spans."""
    init_s = registry.get("engine/init")
    compile_s = registry.get("engine/compile")
    elapsed_s = registry.get("engine/rounds")
    origin_iters = registry.counter("origin_iters")
    if not origin_iters:
        origin_iters = origin_batch * iterations
    value = origin_iters / elapsed_s if elapsed_s > 0 else 0.0
    return {
        "schema": RUN_REPORT_SCHEMA,
        "metric": "origin_iters_per_sec",
        "value": round(value, 2),
        "unit": "origin*iters/s",
        "vs_baseline": round(value / PER_CHIP_TARGET, 4),
        "platform": platform,
        "num_nodes": int(num_nodes),
        "origin_batch": int(origin_batch),
        "iterations": int(iterations),
        "elapsed_s": round(elapsed_s, 3),
        "init_s": round(init_s, 3),
        "compile_s": round(compile_s, 3),
        "compiles": int(registry.counter("engine/compiles")),
        "cache_hits": int(registry.counter("engine/cache_hits")),
    }


def bench_summary(registry, *, platform: str, num_nodes: int,
                  origin_batch: int, iterations: int,
                  coverage_mean: float, rmr_mean: float) -> dict:
    """bench.py's historical one-line JSON, sourced from the registry's
    ``engine/init`` / ``engine/compile`` / ``engine/rounds`` spans."""
    out = _flat_summary(registry, platform=platform, num_nodes=num_nodes,
                        origin_batch=origin_batch, iterations=iterations)
    del out["schema"]  # the bench line predates the report schema
    out["coverage_mean"] = round(coverage_mean, 6)
    out["rmr_mean"] = round(rmr_mean, 6)
    return out


def build_run_report(config, registry, *, stats: dict | None = None,
                     influx: dict | None = None,
                     faults: dict | None = None) -> dict:
    """Assemble the full run report from the span registry + run results.

    ``stats``/``influx``/``faults`` are optional summary dicts the caller
    fills from the stats layer and the Influx sender; absent sections are
    emitted as ``{}`` so the schema stays fixed."""
    snap = registry.snapshot()
    info = snap["info"]
    platform = str(info.get("platform", "unknown"))
    num_nodes = int(info.get("num_nodes", 0))
    origin_batch = int(info.get("origin_batch", 1))
    iterations = int(getattr(config, "gossip_iterations", 0))

    report = _flat_summary(registry, platform=platform, num_nodes=num_nodes,
                           origin_batch=origin_batch, iterations=iterations)
    # lane-mode sweep accounting (engine/lanes.py; 0/0 = serial sweep)
    report["sweep_lanes"] = int(info.get("sweep_lanes", 0))
    report["lane_batches"] = int(info.get("lane_batches", 0))
    rounds_s = registry.get("engine/rounds")
    msgs = registry.counter("messages_delivered")
    wall = snap["wall_s"]
    report.update({
        "coverage_mean": float((stats or {}).get("coverage_mean", 0.0)),
        "rmr_mean": float((stats or {}).get("rmr_mean", 0.0)),
        "config": config_dict(config),
        "environment": environment_info(
            platform=platform, mesh_shape=info.get("mesh_shape")),
        "spans": snap["spans"],
        "counters": snap["counters"],
        "throughput": {
            "origin_iters_per_sec": report["value"],
            "messages_per_sec": round(msgs / rounds_s, 2) if rounds_s > 0
            else 0.0,
            "wall_s": round(wall, 3),
        },
        "faults": dict(faults or {}),
        "influx": dict(influx or {}),
        "stats": dict(stats or {}),
        "compilation_cache": _compilation_cache_section(info),
        "capacity": _capacity_section(info),
        "node_health": _node_health_section(info),
        # resilient-execution accounting (resilience.py): journal units
        # committed this run, units replayed from a prior run's journal,
        # supervised dispatch failures and CPU-fallback re-executions —
        # all zero for an undisturbed, unjournaled run
        "resilience": {
            "committed_units":
                int(registry.counter("resilience/committed_units")),
            "resumed_units":
                int(registry.counter("resilience/resumed_units")),
            "device_failures":
                int(registry.counter("resilience/device_failures")),
            "fallback_units":
                int(registry.counter("resilience/fallback_units")),
        },
        "telemetry": _telemetry_section(info, registry),
        "serve": _serve_section(info),
    })
    return report


def _telemetry_section(info: dict, registry) -> dict:
    """Live telemetry plane accounting (obs/telemetry.py + exporter;
    ISSUE 18): the bound exporter port (0 = exporter never started),
    the event-log path, events emitted and HTTP scrapes served.  Present
    (all-zero) on every report so the schema stays fixed."""
    try:
        from . import telemetry
        hub = telemetry.get_hub()
        return {
            "port": int(info.get("telemetry_port", 0) or 0),
            "event_log": hub.event_log_path,
            "events_emitted": int(hub.events_emitted()),
            "run_fingerprint": hub.run_fingerprint(),
            "scrapes": int(registry.counter("telemetry/scrapes")),
        }
    except Exception:  # pragma: no cover - report must never kill a run
        return {"port": 0, "event_log": "", "events_emitted": 0,
                "run_fingerprint": "", "scrapes": 0}


def _capacity_section(info: dict) -> dict:
    """Capacity-observatory section (obs/capacity.py + obs/memwatch.py):
    the static ledger the run path stamped into registry info, the XLA
    cost-harvest summary and the live-footprint snapshot.  A report must
    never die on a telemetry subsystem, so failures collapse to empty
    subsections."""
    try:
        from . import capacity, memwatch
        return {
            "ledger": dict(info.get("capacity_ledger") or {}),
            "cost": capacity.harvest_summary(),
            "memwatch": memwatch.snapshot(),
        }
    except Exception:  # pragma: no cover - report must never kill a run
        return {"ledger": {}, "cost": {}, "memwatch": {}}


def _node_health_section(info: dict) -> dict:
    """Node-health observatory section (obs/health.py): the digest dict
    the run path stamped into registry info when ``--health`` was on.
    Gated-off runs still carry the section (enabled=False) so the
    REQUIRED-key schema holds on every report."""
    try:
        from .health import HEALTH_SCHEMA
        section = info.get("node_health")
        if section:
            return dict(section)
        return {"schema": HEALTH_SCHEMA, "enabled": False, "topk": 0,
                "source": "", "metrics": {}}
    except Exception:  # pragma: no cover - report must never kill a run
        return {"enabled": False, "metrics": {}}


def _serve_section(info: dict) -> dict:
    """Gossip-as-a-service section (serve/, ISSUE 20): lane occupancy +
    admission counters the daemon stamps into registry info.  Non-serve
    runs still carry the section (enabled=False) so the REQUIRED-key
    schema holds on every report."""
    try:
        section = info.get("serve")
        if section:
            return dict(section)
        return {"enabled": False, "lanes": 0, "busy": 0, "queued": 0,
                "received": 0, "admitted": 0, "rejected": 0,
                "completed": 0}
    except Exception:  # pragma: no cover - report must never kill a run
        return {"enabled": False}


def _compilation_cache_section(info: dict) -> dict:
    """Persistent-cache accounting from registry info (the CLI/bench sync
    engine/cache.py counters into ``info["persistent_cache"]``)."""
    pc = info.get("persistent_cache") or {}
    return {
        "dir": str(info.get("compilation_cache_dir") or ""),
        "hits": int(pc.get("hits", 0)),
        "misses": int(pc.get("misses", 0)),
    }


def write_run_report(path: str, report: dict) -> None:
    """Atomic write (tmp + os.replace), matching checkpoint semantics: a
    SIGKILL mid-write must never leave a truncated, unparseable report
    where a previous good one stood."""
    import os
    import tempfile
    payload = json.dumps(report, indent=2, sort_keys=False) + "\n"
    fd, tmp = tempfile.mkstemp(prefix=".report-", suffix=".json",
                               dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def validate_run_report(report: dict) -> list:
    """Schema check: returns a list of problems (empty == valid)."""
    problems = []
    if not isinstance(report, dict):
        return [f"report is {type(report).__name__}, not dict"]
    for key, types in REQUIRED_KEYS.items():
        if key not in report:
            problems.append(f"missing key: {key}")
        elif not isinstance(report[key], types):
            problems.append(
                f"key {key}: expected {types}, got "
                f"{type(report[key]).__name__}")
    if report.get("schema") not in (None, RUN_REPORT_SCHEMA):
        problems.append(f"unknown schema: {report.get('schema')!r}")
    for name, ent in (report.get("spans") or {}).items():
        if (not isinstance(ent, dict) or "total_s" not in ent
                or "count" not in ent):
            problems.append(f"span {name}: needs total_s + count")
    thr = report.get("throughput")
    if isinstance(thr, dict):
        for k in ("origin_iters_per_sec", "messages_per_sec", "wall_s"):
            if not isinstance(thr.get(k), (int, float)):
                problems.append(f"throughput.{k} missing or non-numeric")
    try:
        json.dumps(report)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    return problems
