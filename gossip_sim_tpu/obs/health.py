"""Node health observatory: per-node load, latency, and drop attribution.

The engine accumulates O(N) health planes inside the jitted round scan
(engine/core.py, engine/traffic.py — behind the static
``EngineStatic.health`` gate), and this module turns those planes into
small host-harvestable digests **on device**:

* stake-decile segment sums over the precomputed ``ClusterTables.
  stake_decile`` id table — the host only ever sees a ``[P, 10]`` array
  (P = number of metrics), never the raw ``[N]`` planes;
* top-k hot-node extraction per metric (``lax.top_k`` — ties break
  toward the lower node id, matching the numpy twin's lexsort);
* load-imbalance Gini as an exact integer numerator/denominator pair
  (the i64 sums are order-independent, so the device and the numpy twin
  agree bit-for-bit; the one float division happens on the host).

Everything here has a loop/numpy twin (`digest_stack_np`) used by the
oracle parity tests (tests/test_health.py) and by ``tools/
health_report.py`` when re-deriving digests from raw report planes.

Like the rest of :mod:`gossip_sim_tpu.obs`, importing this module stays
JAX-free — the device path imports JAX lazily inside the jitted-builder
so bench.py's parent process never touches it.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

HEALTH_SCHEMA = "gossip-sim-tpu/node-health/v1"

#: number of stake-decile segments (ClusterTables.stake_decile ids)
NUM_DECILES = 10

#: default hot-node extraction width (Config.health_topk)
DEFAULT_TOPK = 10

__all__ = [
    "HEALTH_SCHEMA", "NUM_DECILES", "DEFAULT_TOPK",
    "stake_decile_ids", "digest_stack", "digest_stack_np",
    "decile_sums_np", "topk_nodes_np", "gini_parts_np", "gini_value",
    "build_node_health_section", "influx_values",
]


# --------------------------------------------------------------------------
# the decile id table (numpy twin of engine/core.py make_cluster_tables)
# --------------------------------------------------------------------------

def stake_decile_ids(stakes) -> np.ndarray:
    """[N] i32 stake-rank decile ids: stable ascending sort (equal stakes
    tie-break by node id), decile 0 = the lowest-staked tenth.  This is
    the exact computation ``make_cluster_tables`` bakes into
    ``ClusterTables.stake_decile`` — one id map shared by the engine and
    every loop oracle."""
    stakes = np.asarray(stakes, dtype=np.int64)
    n = stakes.shape[0]
    order = np.argsort(stakes, kind="stable")
    rank = np.empty(n, dtype=np.int64)
    rank[order] = np.arange(n, dtype=np.int64)
    return (rank * 10 // n).astype(np.int32)


# --------------------------------------------------------------------------
# numpy twins (exact integer math — the parity reference)
# --------------------------------------------------------------------------

def decile_sums_np(metric, decile_ids) -> np.ndarray:
    """[10] i64 per-decile sums of one [N] metric plane."""
    out = np.zeros(NUM_DECILES, dtype=np.int64)
    np.add.at(out, np.asarray(decile_ids), np.asarray(metric, np.int64))
    return out


def topk_nodes_np(metric, k: int):
    """Top-k hot nodes of one [N] plane -> (idx [k] i32, val [k] i64).
    Ties break toward the lower node id (lax.top_k's documented order)."""
    metric = np.asarray(metric, dtype=np.int64)
    n = metric.shape[0]
    k = min(int(k), n)
    order = np.lexsort((np.arange(n), -metric))[:k]
    return order.astype(np.int32), metric[order]


def gini_parts_np(metric):
    """Exact integer Gini parts of one [N] plane -> (num, den) i64 with
    ``gini = num / den`` (0 when den == 0).  Formulation: sort ascending,
    ``num = sum((2i - n - 1) * x_i)``, ``den = n * sum(x)`` — every term
    is an exact i64, so summation order cannot matter and the device twin
    matches bit-for-bit."""
    xs = np.sort(np.asarray(metric, dtype=np.int64))
    n = xs.shape[0]
    w = 2 * np.arange(1, n + 1, dtype=np.int64) - n - 1
    return int(np.sum(w * xs)), int(n * np.sum(xs))


def gini_value(num: int, den: int) -> float:
    """The one float division, shared by both paths."""
    return float(num) / float(den) if den else 0.0


def digest_stack_np(stack, decile_ids, k: int) -> dict:
    """Loop/numpy twin of :func:`digest_stack` over a [P, N] i64-able
    stack.  Returns the identical integer arrays."""
    stack = np.asarray(stack, dtype=np.int64)
    dec = np.stack([decile_sums_np(row, decile_ids) for row in stack])
    idx, val = zip(*(topk_nodes_np(row, k) for row in stack))
    gnum, gden = zip(*(gini_parts_np(row) for row in stack))
    return {
        "deciles": dec,                                   # [P, 10] i64
        "top_idx": np.stack(idx),                         # [P, k]  i32
        "top_val": np.stack(val),                         # [P, k]  i64
        "gini_num": np.asarray(gnum, np.int64),           # [P]     i64
        "gini_den": np.asarray(gden, np.int64),           # [P]     i64
    }


# --------------------------------------------------------------------------
# the on-device digest (lazy-JAX; one dispatch per measured block)
# --------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _device_digest_fn(k: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(stack, decile_ids):
        # stack [P, N] i32/i64 -> everything the host ever reads is
        # [P, 10] / [P, k] / [P]: zero O(N) host transfers.
        stack = stack.astype(jnp.int64)
        dec = jax.ops.segment_sum(
            stack.T, decile_ids.astype(jnp.int32),
            num_segments=NUM_DECILES).T                   # [P, 10] i64
        top_val, top_idx = jax.lax.top_k(stack, k)        # ties -> low id
        xs = jnp.sort(stack, axis=-1)
        n = stack.shape[-1]
        w = 2 * jnp.arange(1, n + 1, dtype=jnp.int64) - n - 1
        gnum = jnp.sum(w[None, :] * xs, axis=-1)
        gden = n * jnp.sum(xs, axis=-1)
        return dec, top_idx.astype(jnp.int32), top_val, gnum, gden

    return run


def digest_stack(stack, decile_ids, k: int) -> dict:
    """On-device digest of a [P, N] metric stack (device arrays in, small
    host numpy arrays out).  Bit-identical to :func:`digest_stack_np` on
    the same integers."""
    import jax
    n = int(np.shape(stack)[-1])
    k = min(int(k), n)
    if not jax.config.jax_enable_x64:
        # without x64 the device i64 sums would silently truncate to i32
        # and the exact-integer parity contract breaks — engine callers
        # always have x64 (engine/__init__ flips it on import), so this
        # fallback only covers digesting outside an engine process
        return digest_stack_np(np.asarray(stack), np.asarray(decile_ids), k)
    dec, idx, val, gnum, gden = _device_digest_fn(k)(stack, decile_ids)
    return {
        "deciles": np.asarray(dec),
        "top_idx": np.asarray(idx),
        "top_val": np.asarray(val),
        "gini_num": np.asarray(gnum),
        "gini_den": np.asarray(gden),
    }


# --------------------------------------------------------------------------
# report / wire assembly (host-side, numpy-only)
# --------------------------------------------------------------------------

def build_node_health_section(metric_names, digest, *, enabled: bool,
                              topk: int, source: str,
                              latency: dict | None = None,
                              extra: dict | None = None) -> dict:
    """Assemble the REQUIRED ``node_health`` run-report section.

    ``digest`` is a :func:`digest_stack` / :func:`digest_stack_np` result
    whose row order matches ``metric_names``.  ``latency`` optionally
    carries the decile coverage-latency table ({"lat_sum": [10],
    "delivered": [10]} style pairs already reduced to deciles).  When the
    gate is off the section still exists (schema + enabled=False) so
    ``validate_run_report`` holds on every run."""
    section: dict = {
        "schema": HEALTH_SCHEMA,
        "enabled": bool(enabled),
        "topk": int(topk),
        "source": str(source),
        "metrics": {},
    }
    if not enabled or digest is None:
        return section
    for i, name in enumerate(metric_names):
        section["metrics"][name] = {
            "total": int(digest["deciles"][i].sum()),
            "deciles": [int(x) for x in digest["deciles"][i]],
            "hot_nodes": [
                {"node": int(a), "count": int(b)}
                for a, b in zip(digest["top_idx"][i], digest["top_val"][i])
            ],
            "gini": gini_value(int(digest["gini_num"][i]),
                               int(digest["gini_den"][i])),
        }
    if latency:
        section["latency"] = latency
    if extra:
        section.update(extra)
    return section


def influx_values(metric_names, digest, *, topk: int) -> dict:
    """Flatten a digest into the ``sim_node_health`` point's field dict
    (sorted-key emission happens in sinks/influx.py).  Totals and Gini
    per metric, plus the hot-node (id, count) pairs of every metric so
    drop attribution is replayable per block."""
    vals: dict = {}
    for i, name in enumerate(metric_names):
        vals[f"{name}_total"] = int(digest["deciles"][i].sum())
        vals[f"{name}_gini"] = gini_value(int(digest["gini_num"][i]),
                                          int(digest["gini_den"][i]))
        for j in range(min(int(topk), digest["top_idx"].shape[1])):
            vals[f"{name}_hot{j}_node"] = int(digest["top_idx"][i, j])
            vals[f"{name}_hot{j}_count"] = int(digest["top_val"][i, j])
    return vals
