"""Live memory-footprint sampler (``--memwatch-interval-s``).

A low-overhead daemon thread polling, at a configurable interval:

* **host RSS** — ``/proc/self/statm`` (one read + split, ~10 us; falls
  back to ``resource.getrusage`` where /proc is absent), and
* **device memory** — ``jax.local_devices()[i].memory_stats()``
  ``bytes_in_use`` where the backend reports it (TPU/GPU; CPU returns
  None and is skipped).  JAX is only consulted when the process already
  imported it — the sampler never forces a backend up on its own (the
  obs/report.py discipline).

Recorded per run: the peak and a bounded, auto-decimating time series
(when the buffer fills, every other sample is dropped and the keep
stride doubles — a 10-hour run still fits ``max_series`` points).  The
kernel's own high-water mark (``VmHWM`` / ``ru_maxrss``) rides along in
every snapshot, so run reports carry a true peak-RSS figure even when
the sampler never ran.

Thread-discipline follows obs/spans.py: one lock around the aggregate
state, samples never raise into the run, ``stop()`` joins the thread.
The module-level singleton is what the CLI and bench share (one process
== one run); tests construct private instances.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


#: persistent /proc/self/statm fd: opening a procfs file costs ~0.8 ms
#: CPU in sandboxed kernels while an os.pread on a kept-open fd is
#: ~30 us — the difference between a <0.2% and a >15% sampler duty
#: cycle at a 20 ms interval.  /proc/self never goes stale.
_statm_fd: int | None = None


def rss_bytes() -> int:
    """Current resident set size of this process, in bytes (0 if
    unreadable — never raises)."""
    global _statm_fd
    try:
        if _statm_fd is None:
            _statm_fd = os.open("/proc/self/statm", os.O_RDONLY)
        return int(os.pread(_statm_fd, 128, 0).split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return int(ru.ru_maxrss) * 1024  # peak, but better than 0
    except Exception:
        return 0


def peak_rss_bytes() -> int:
    """Kernel high-water-mark RSS (``VmHWM``; ``ru_maxrss`` fallback).
    Exact and free — no sampling needed for the peak itself."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmHWM:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return int(ru.ru_maxrss) * 1024
    except Exception:
        return 0


#: None = not yet probed; [] = devices keep no memory stats (CPU — skip
#: polling forever); list = the stat-bearing devices to poll.  Resolving
#: ``jax.local_devices()`` costs milliseconds per call, so probing once
#: is what keeps the per-sample cost at a /proc read (the <2% exact-
#: accounting bound tools/capacity_smoke.py enforces).
_stat_devices: list | None = None


def device_memory_bytes() -> int:
    """Sum of ``bytes_in_use`` across local devices, 0 where the backend
    keeps no stats (CPU) or JAX never came up.  Never initializes a
    backend: consulted only when jax is already imported; the device
    list is probed once per process."""
    global _stat_devices
    if "jax" not in sys.modules:
        return 0
    try:
        if _stat_devices is None:
            import jax
            _stat_devices = [
                d for d in jax.local_devices()
                if hasattr(d, "memory_stats") and d.memory_stats()]
        total = 0
        for d in _stat_devices:
            stats = d.memory_stats()
            if stats:
                total += int(stats.get("bytes_in_use", 0))
        return total
    except Exception:  # pragma: no cover - backend-dependent
        return 0


class MemWatch:
    """The sampler thread + bounded series store."""

    def __init__(self, interval_s: float = 0.5, max_series: int = 512):
        self.interval_s = max(0.005, float(interval_s))
        self.max_series = max(16, int(max_series))
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._series: list = []          # [(t_rel, rss_bytes), ...]
        self._stride = 1                 # decimation: keep 1-in-stride
        self._tick = 0
        self._samples = 0
        self._peak_rss = 0
        self._peak_device = 0
        self._last_rss = 0
        self._sample_time_s = 0.0
        self._t0 = time.perf_counter()

    # -- sampling ---------------------------------------------------------

    def sample_once(self) -> int:
        """Take one sample (also usable without the thread); returns the
        sampled RSS.  ``sample_time_s`` accumulates thread CPU time
        (``time.thread_time``), not wall — under a saturated box the
        sampler's wall includes GIL/scheduler waits that cost the run
        nothing, and the <2% overhead bound is about CPU actually
        consumed.  The CPU clock itself is a slow syscall in sandboxed
        kernels (~0.3 ms contended — several times the sample it
        measures), so self-timing runs on every 8th sample and scales
        by 8: the accounting stays honest while the act of measuring
        stops dominating the cost being measured."""
        measure = (self._tick & 7) == 0
        t0 = time.thread_time() if measure else 0.0
        wall0 = time.perf_counter()
        rss = rss_bytes()
        dev = device_memory_bytes()
        with self._lock:
            self._samples += 1
            self._last_rss = rss
            self._peak_rss = max(self._peak_rss, rss)
            self._peak_device = max(self._peak_device, dev)
            if self._tick % self._stride == 0:
                self._series.append((round(wall0 - self._t0, 3), rss))
                if len(self._series) >= self.max_series:
                    # decimate: drop every other point, double the stride
                    self._series = self._series[::2]
                    self._stride *= 2
            self._tick += 1
            if measure:
                self._sample_time_s += (time.thread_time() - t0) * 8
        return rss

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # pragma: no cover - must never kill a run
                pass

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "MemWatch":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._t0 = time.perf_counter()
        self.sample_once()               # a run is never sample-free
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="memwatch")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self.sample_once()               # close the series at stop time

    @property
    def running(self) -> bool:
        return self._thread is not None

    # -- reporting --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-safe state for the run report's ``capacity.memwatch``
        section.  ``peak_rss_bytes`` is the max of the sampled peak and
        the kernel high-water mark, so it is nonzero (and honest) even
        when the sampler never ran."""
        kernel_peak = peak_rss_bytes()
        with self._lock:
            return {
                "enabled": self._thread is not None or self._samples > 0,
                "interval_s": self.interval_s if self._samples > 1 else 0.0,
                "samples": self._samples,
                "peak_rss_bytes": max(self._peak_rss, kernel_peak),
                "sampled_peak_rss_bytes": self._peak_rss,
                "kernel_peak_rss_bytes": kernel_peak,
                "last_rss_bytes": self._last_rss or rss_bytes(),
                "peak_device_bytes": self._peak_device,
                "sample_time_s": round(self._sample_time_s, 6),
                "series_stride": self._stride,
                "rss_series": [list(p) for p in self._series],
            }


_SINGLETON = MemWatch()


def get_memwatch() -> MemWatch:
    """The process-wide sampler (one process == one run)."""
    return _SINGLETON


def reset() -> None:
    """One process == one run (the span-registry discipline): stop any
    sampler a previous in-process run left behind — including one leaked
    by an early-exit path — and drop its series, so the next run's
    snapshots never carry another run's data."""
    global _SINGLETON
    _SINGLETON.stop()
    _SINGLETON = MemWatch()


def start(interval_s: float) -> MemWatch:
    """Start (or retune + start) the shared sampler."""
    global _SINGLETON
    if _SINGLETON.running:
        return _SINGLETON
    if _SINGLETON._samples:
        _SINGLETON = MemWatch(interval_s)   # fresh series per run
    else:
        _SINGLETON.interval_s = max(0.005, float(interval_s))
    return _SINGLETON.start()


def stop() -> None:
    _SINGLETON.stop()


def snapshot() -> dict:
    """Snapshot of the shared sampler — safe (and meaningful: kernel
    peak + current RSS) even when no sampler ever started."""
    return _SINGLETON.snapshot()
