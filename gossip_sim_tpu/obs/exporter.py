"""Zero-dependency HTTP exporter for the live telemetry plane (ISSUE 18).

A stdlib-only (``http.server``) background thread behind
``--telemetry-port`` serving three read-only endpoints off the
:mod:`gossip_sim_tpu.obs.telemetry` hub:

* ``/metrics`` — Prometheus text exposition (format 0.0.4) of the hub
  snapshot: span totals, counters, progress/ETA gauges, RSS, live
  Influx sender stats, event counts.
* ``/status``  — the evolving run-report as JSON, mid-run (the same
  ``gossip-sim-tpu/run-report/v1`` document ``--run-report`` writes at
  exit, assembled live on each scrape).
* ``/events``  — the most recent structured events (ring buffer; works
  with or without ``--event-log``).  ``?n=N`` bounds the count.

Port 0 binds an ephemeral port; the bound port is returned from
:meth:`TelemetryServer.start`, stamped into the log, registry info
(``telemetry_port``) and the run report's ``telemetry`` section, and
emitted as a ``telemetry_listen`` event so tools can discover it from
the event log alone.

The server binds 127.0.0.1 (an introspection surface, not an ingress),
swallows per-request errors (a scrape must never kill a run), and keeps
request handling off the simulation thread entirely — the <2% overhead
contract is enforced by tools/telemetry_smoke.py.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .spans import get_registry
from .telemetry import get_hub

log = logging.getLogger("gossip_sim_tpu.obs")

#: Prometheus text exposition content type (format 0.0.4)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PREFIX = "gossip_sim"


def _escape_label(value: str) -> str:
    """Escape a Prometheus label value (backslash, quote, newline)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _num(value) -> str:
    """Render a number in exposition format (no inf/nan surprises)."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return "0"
    if f != f:                       # NaN
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(snap: dict) -> str:
    """Render a hub snapshot as Prometheus text exposition lines."""
    lines = []

    def metric(name, mtype, help_text, samples):
        # samples: list of (label_dict_or_None, value)
        rendered = []
        for labels, value in samples:
            if labels:
                lab = ",".join(f'{k}="{_escape_label(v)}"'
                               for k, v in sorted(labels.items()))
                rendered.append(f"{_PREFIX}_{name}{{{lab}}} {_num(value)}")
            else:
                rendered.append(f"{_PREFIX}_{name} {_num(value)}")
        if rendered:
            lines.append(f"# HELP {_PREFIX}_{name} {help_text}")
            lines.append(f"# TYPE {_PREFIX}_{name} {mtype}")
            lines.extend(rendered)

    run = snap.get("run", {})
    metric("info", "gauge", "Run identity (constant 1).",
           [({"platform": run.get("platform", "unknown"),
              "run_path": run.get("run_path", ""),
              "fingerprint": run.get("fingerprint", "")}, 1)])
    metric("wall_seconds", "gauge", "Wall seconds since registry reset.",
           [(None, run.get("wall_s", 0))])
    metric("num_nodes", "gauge", "Simulated cluster size.",
           [(None, run.get("num_nodes", 0))])

    spans = snap.get("spans", {})
    metric("span_seconds_total", "counter",
           "Total seconds recorded per span.",
           [({"span": name}, ent.get("total_s", 0))
            for name, ent in spans.items()])
    metric("span_calls_total", "counter", "Span entry count.",
           [({"span": name}, ent.get("count", 0))
            for name, ent in spans.items()])
    metric("counter_total", "counter", "Raw registry counters.",
           [({"counter": name}, val)
            for name, val in snap.get("counters", {}).items()])

    progress_samples = {"done": [], "total": [], "pct": [],
                        "rate": [], "eta_seconds": []}
    for label, st in snap.get("progress", {}).items():
        progress_samples["done"].append(({"label": label},
                                         st.get("done", 0)))
        progress_samples["total"].append(({"label": label},
                                          st.get("total", 0)))
        progress_samples["pct"].append(({"label": label},
                                        st.get("pct", 0)))
        progress_samples["rate"].append(({"label": label},
                                         st.get("rate_per_s", 0)))
        eta = st.get("eta_s")
        progress_samples["eta_seconds"].append(
            ({"label": label}, -1 if eta is None else eta))
    metric("progress_done", "gauge", "Units completed per loop.",
           progress_samples["done"])
    metric("progress_total", "gauge", "Units planned per loop.",
           progress_samples["total"])
    metric("progress_pct", "gauge", "Percent complete per loop.",
           progress_samples["pct"])
    metric("progress_rate", "gauge", "Units per second per loop.",
           progress_samples["rate"])
    metric("progress_eta_seconds", "gauge",
           "Estimated seconds remaining (-1 = unknown).",
           progress_samples["eta_seconds"])

    mw = snap.get("memwatch", {})
    metric("rss_bytes", "gauge", "Current resident set size.",
           [(None, mw.get("rss_bytes", 0))])
    metric("peak_rss_bytes", "gauge", "Peak resident set size.",
           [(None, mw.get("peak_rss_bytes", 0))])
    metric("peak_device_bytes", "gauge", "Peak device bytes in use.",
           [(None, mw.get("peak_device_bytes", 0))])

    cap = snap.get("capacity", {})
    metric("capacity_ledger_bytes", "gauge",
           "Closed-form donated-buffer ledger total.",
           [(None, cap.get("ledger_total_bytes", 0))])

    influx = snap.get("influx", {})
    if influx:
        metric("influx_points_sent_total", "counter",
               "Datapoints sent by the Influx sender.",
               [(None, influx.get("points_sent", 0))])
        metric("influx_points_dropped_total", "counter",
               "Datapoints dropped by the Influx sender.",
               [(None, influx.get("dropped_points", 0))])
        metric("influx_points_spooled_total", "counter",
               "Datapoints spooled to disk by the Influx sender.",
               [(None, influx.get("spooled_points", 0))])
        metric("influx_retries_total", "counter",
               "Influx sender POST retries.",
               [(None, influx.get("retries", 0))])
        metric("influx_queue_depth", "gauge",
               "Datapoints waiting in the sender queue.",
               [(None, influx.get("queue_depth", 0))])

    res = snap.get("resilience", {})
    metric("journal_committed_units_total", "counter",
           "Units durably committed to the run journal.",
           [(None, res.get("committed_units", 0))])
    metric("journal_resumed_units_total", "counter",
           "Units replayed from a prior run's journal.",
           [(None, res.get("resumed_units", 0))])
    metric("device_failures_total", "counter",
           "Supervised dispatch failures.",
           [(None, res.get("device_failures", 0))])

    ev = snap.get("events", {})
    metric("events_emitted_total", "counter",
           "Structured events emitted this run.",
           [(None, ev.get("emitted", 0))])

    serve = snap.get("serve", {})
    if serve:
        metric("serve_lanes", "gauge", "Configured serve lane count.",
               [(None, serve.get("lanes", 0))])
        metric("serve_lanes_busy", "gauge",
               "Lanes currently executing a request.",
               [(None, serve.get("busy", 0))])
        metric("serve_queue_depth", "gauge",
               "Requests admitted but waiting for a lane.",
               [(None, serve.get("queued", 0))])
        metric("serve_requests_received_total", "counter",
               "Scenario requests received.",
               [(None, serve.get("received", 0))])
        metric("serve_requests_admitted_total", "counter",
               "Scenario requests admitted into a lane.",
               [(None, serve.get("admitted", 0))])
        metric("serve_requests_rejected_total", "counter",
               "Scenario requests rejected by admission control.",
               [(None, serve.get("rejected", 0))])
        metric("serve_requests_completed_total", "counter",
               "Scenario requests completed.",
               [(None, serve.get("completed", 0))])
        metric("serve_tenant_admitted_total", "counter",
               "Requests admitted per tenant.",
               [({"tenant": t}, v) for t, v in
                sorted(serve.get("tenants_admitted", {}).items())])
        metric("serve_tenant_rejected_total", "counter",
               "Requests rejected per tenant.",
               [({"tenant": t}, v) for t, v in
                sorted(serve.get("tenants_rejected", {}).items())])

    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict:
    """Parse exposition text back into ``{name: {labelset: value}}``
    (labelset = the raw ``{...}`` string, '' for bare samples).  Strict
    enough to be the smoke gate's validity check: every non-comment line
    must be ``name[{labels}] value`` with a parseable float value and a
    legal metric name."""
    import re
    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    out: dict = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        body, _, value = line.rpartition(" ")
        if not body:
            raise ValueError(f"line {i}: no metric/value split: {line!r}")
        if "{" in body:
            name, _, rest = body.partition("{")
            if not rest.endswith("}"):
                raise ValueError(f"line {i}: unterminated labels")
            labels = "{" + rest
        else:
            name, labels = body, ""
        if not name_re.match(name):
            raise ValueError(f"line {i}: bad metric name {name!r}")
        out.setdefault(name, {})[labels] = float(value)
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "gossip-sim-telemetry/1"

    def _dispatch_custom(self, routes, url, body=None) -> bool:
        """Try the server's pluggable routes (the serve daemon mounts
        /submit, /result/<id>, /serve here).  Exact path match first,
        then prefix routes (keys ending "/") with the tail passed
        through.  Handlers return ``(code, payload)``; a dict/list
        payload goes out as JSON, bytes/str verbatim."""
        fn = routes.get(url.path)
        arg = None
        if fn is None:
            for key, cand in routes.items():
                if key.endswith("/") and url.path.startswith(key):
                    fn, arg = cand, url.path[len(key):]
                    break
        if fn is None:
            return False
        kwargs = {"query": parse_qs(url.query)}
        if arg is not None:
            kwargs["tail"] = arg
        if body is not None:
            kwargs["body"] = body
        code, payload = fn(**kwargs)
        if isinstance(payload, (dict, list)):
            self._reply(int(code), "application/json",
                        (json.dumps(payload, default=str) + "\n")
                        .encode("utf-8"))
        else:
            if isinstance(payload, str):
                payload = payload.encode("utf-8")
            self._reply(int(code), "text/plain", payload)
        return True

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        try:
            url = urlparse(self.path)
            if self._dispatch_custom(
                    getattr(self.server, "get_routes", {}), url):
                return
            if url.path == "/metrics":
                body = prometheus_text(self.server.hub.snapshot())
                self._reply(200, PROMETHEUS_CONTENT_TYPE,
                            body.encode("utf-8"))
            elif url.path == "/status":
                status = self.server.status()
                self._reply(200, "application/json",
                            (json.dumps(status, default=str) + "\n")
                            .encode("utf-8"))
            elif url.path == "/events":
                n = 100
                q = parse_qs(url.query)
                if "n" in q:
                    try:
                        n = max(0, min(int(q["n"][0]), 100000))
                    except ValueError:
                        pass
                events = self.server.hub.recent_events(n)
                self._reply(200, "application/json",
                            (json.dumps({"schema":
                                         self.server.event_schema,
                                         "events": events},
                                        default=str) + "\n")
                            .encode("utf-8"))
            elif url.path in ("/", "/healthz"):
                self._reply(200, "text/plain", b"ok\n")
            else:
                self._reply(404, "text/plain", b"not found\n")
        except Exception as e:  # pragma: no cover - scrape never kills run
            try:
                self._reply(500, "text/plain",
                            f"telemetry error: {e}\n".encode("utf-8"))
            except Exception:
                pass

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
        try:
            url = urlparse(self.path)
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            body = self.rfile.read(max(0, min(length, 1 << 20)))
            if not self._dispatch_custom(
                    getattr(self.server, "post_routes", {}), url,
                    body=body):
                self._reply(404, "text/plain", b"not found\n")
        except Exception as e:  # pragma: no cover - intake never kills run
            try:
                self._reply(500, "text/plain",
                            f"telemetry error: {e}\n".encode("utf-8"))
            except Exception:
                pass

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        get_registry().add("telemetry/scrapes")

    def log_message(self, fmt, *args):  # quiet: requests go to debug
        log.debug("telemetry http: " + fmt, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # ephemeral-port churn in tests: reuse addresses aggressively
    allow_reuse_address = True


class TelemetryServer:
    """The background HTTP exporter.  ``start()`` binds and returns the
    port; ``stop()`` shuts the serve loop down and joins the thread."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 status_fn=None, hub=None):
        self.requested_port = int(port)
        self.host = host
        self.hub = hub if hub is not None else get_hub()
        self._status_fn = status_fn
        self._httpd = None
        self._thread = None
        self.port = 0
        # pluggable endpoints (the serve daemon's HTTP intake): shared
        # dicts so add_route works before AND after start()
        self._get_routes: dict = {}
        self._post_routes: dict = {}

    def add_route(self, method: str, path: str, fn) -> None:
        """Mount a handler at ``path`` ("GET"/"POST").  A path ending
        "/" is a prefix route; the remainder arrives as ``tail=``.
        Handlers receive ``query=`` (parsed), ``body=`` (POST bytes) and
        return ``(status_code, payload)``."""
        routes = (self._post_routes if method.upper() == "POST"
                  else self._get_routes)
        routes[path] = fn

    def _status(self) -> dict:
        if self._status_fn is None:
            return self.hub.snapshot()
        try:
            return self._status_fn()
        except Exception as e:  # pragma: no cover - scrape never kills run
            return {"error": f"status assembly failed: {e}"}

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        from .telemetry import EVENT_SCHEMA
        httpd = _Server((self.host, self.requested_port), _Handler)
        httpd.hub = self.hub
        httpd.status = self._status
        httpd.event_schema = EVENT_SCHEMA
        httpd.get_routes = self._get_routes
        httpd.post_routes = self._post_routes
        self._httpd = httpd
        self.port = httpd.server_address[1]
        # a tight poll keeps stop() latency ~50ms worst-case — teardown
        # is on the run's critical path and counts against the <2%
        # overhead budget on short runs (the idle select() is free)
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        kwargs={"poll_interval": 0.05},
                                        daemon=True, name="telemetry-http")
        self._thread.start()
        log.info("telemetry: serving /metrics /status /events on "
                 "http://%s:%d", self.host, self.port)
        self.hub.emit("telemetry_listen", port=self.port, host=self.host)
        get_registry().set_info("telemetry_port", self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is None:
            return
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        finally:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            self._httpd = None
            self._thread = None

    @property
    def running(self) -> bool:
        return self._httpd is not None
