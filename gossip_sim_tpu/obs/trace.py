"""Protocol flight recorder: message-level delivery/prune traces.

The stats layer answers *what* a topology did (coverage, RMR, LDH,
stranded counts); this module records *why*: with ``--trace-dir`` set, every
measured round's protocol events are captured as fixed-shape arrays inside
the engine's ``round_step`` (or rebuilt from the oracle's per-round state)
and written as versioned ``.npz`` segments plus a JSON manifest, so any run
can be replayed and root-caused offline with ``tools/trace_report.py`` —
no re-run with print statements.

Per traced round (leading axes ``[rounds, O]``; node ids are int16,
``-1`` = none/empty):

* ``peers``   [O,N,F]  candidate push target per fanout slot — the first F
                       valid (unpruned, non-origin) active-set slots, the
                       exact list verb 1 pushed through this round
* ``code``    [O,N,F]  per-slot outcome: 0 empty, 1 deliverable candidate,
                       2 failed target, 3 partition-suppressed, 4 loss-
                       dropped (precedence matches faults.classify_edge);
                       a candidate actually delivers iff its source was
                       reached this round (``dist[src] >= 0``)
* ``dist``    [O,N]    hop distance from the origin (-1 unreached)
* ``first_src`` [O,N]  first-delivery sender: the minimum (hop, src-index)
                       inbound edge — identical to the reference's
                       (hops, pubkey-string) consume ranking because
                       NodeIndex assigns indices in pubkey-string order
* ``failed``  [O,N]    node-failure mask after this round's churn/fail step
* ``rot``     [O,N]    rotation events: engine = rotated-in peer id;
                       oracle = 1 for nodes that re-sampled (its rotation
                       replaces the whole entry, a documented divergence)
* ``active``  [O,N,S]  PRE-round active-set snapshot (what verb 1 consulted)
* ``pruned``  [O,N,S]  PRE-round per-slot pruned bits for this origin
* ``prune_src``/``prune_dst`` [O,P]  prune pairs emitted this round
                       (pruner, prunee); P = ``EngineParams.prune_cap``
                       slots, overflow flagged in the manifest, never
                       silently dropped
* ``coverage`` [O]     fraction reached (cross-check vs the stats layer)
* ``prunes_total`` [O] total prune messages (the ``prunes_sent`` row)

Segments are written atomically (temp + ``os.replace``, like checkpoints)
and named by round range, so a ``--resume`` continuation appends new
segments without duplicating or losing already-traced rounds.  The
manifest (``manifest.json``, schema ``gossip-sim-tpu/trace/v1``) is keyed
to the run-report schema from obs/report.py: it embeds the same JSON-safe
``config`` block and cross-references ``run_report_schema`` so a trace and
its run report can always be joined on (seed, config, round range).

Everything here is numpy-only: importing this module (and the ``obs``
package) never touches JAX.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile

import numpy as np

from ..pull import PULL_CODE_NAMES
from .report import RUN_REPORT_SCHEMA, config_dict

log = logging.getLogger("gossip_sim_tpu.obs")

# v2 (pull-gossip subsystem): adds the pull request/response event arrays
# (``pull_peers``/``pull_code``/``pull_hop``) plus the ``gossip_mode`` /
# ``pull_slots`` manifest keys.  v3 (concurrent traffic, traffic.py): adds
# the value-id column — traffic-mode traces carry per-value-slot event
# arrays (``value_id``/``value_origin`` identify each slot's in-flight
# value per round; delivery and prune arrays gain a leading V axis) and
# the ``traffic_slots`` manifest key.  v4 (adaptive push-pull,
# adaptive.py): adds the switch-round events — single-origin adaptive
# traces carry the per-round direction bit (``adaptive_on``), traffic
# adaptive traces the per-value phase bit (``value_pull``) and per-node
# rescue deliveries (``pull_hop`` with a V axis).  New traces are written
# as v4 (adaptive arrays present only under gossip_mode "adaptive");
# v1/v2/v3 remain readable.
TRACE_SCHEMA_V1 = "gossip-sim-tpu/trace/v1"
TRACE_SCHEMA_V2 = "gossip-sim-tpu/trace/v2"
TRACE_SCHEMA_V3 = "gossip-sim-tpu/trace/v3"
TRACE_SCHEMA = "gossip-sim-tpu/trace/v4"
READABLE_SCHEMAS = (TRACE_SCHEMA_V1, TRACE_SCHEMA_V2, TRACE_SCHEMA_V3,
                    TRACE_SCHEMA)
MANIFEST_NAME = "manifest.json"

# per-slot outcome codes (shared with engine/core.py round_step and the
# oracle collector; precedence: failed target > suppressed > dropped)
TRACE_EMPTY = 0
TRACE_CANDIDATE = 1
TRACE_FAILED_TARGET = 2
TRACE_SUPPRESSED = 3
TRACE_DROPPED = 4
TRACE_CODE_NAMES = {
    TRACE_EMPTY: "empty",
    TRACE_CANDIDATE: "candidate",
    TRACE_FAILED_TARGET: "failed_target",
    TRACE_SUPPRESSED: "suppressed",
    TRACE_DROPPED: "dropped",
}

#: segment arrays: name -> (on-disk dtype, symbolic per-round shape suffix).
#: Dims: N nodes, F push fanout, S active-set size, P prune_cap.
ARRAY_SPECS = {
    "peers": ("int16", ("N", "F")),
    "code": ("int8", ("N", "F")),
    "dist": ("int16", ("N",)),
    "first_src": ("int16", ("N",)),
    "failed": ("bool", ("N",)),
    "rot": ("int16", ("N",)),
    "active": ("int16", ("N", "S")),
    "pruned": ("bool", ("N", "S")),
    "prune_src": ("int16", ("P",)),
    "prune_dst": ("int16", ("P",)),
    "coverage": ("float32", ()),
    "prunes_total": ("int32", ()),
}

#: v2 pull-phase arrays (pull.py), present when the manifest's
#: ``gossip_mode`` includes a pull phase.  Dims: Q = pull_slots.
PULL_ARRAY_SPECS = {
    "pull_peers": ("int16", ("N", "Q")),
    "pull_code": ("int8", ("N", "Q")),
    "pull_hop": ("int16", ("N",)),
}

#: v3 concurrent-traffic arrays (traffic.py), used INSTEAD of the base
#: specs when the manifest's ``traffic_slots`` > 0.  Dims: V = value
#: slots.  ``value_id``/``value_origin`` are the value-id column: the
#: per-round identity of each slot's in-flight value (-1 = free slot), so
#: every delivery/prune event row is value-attributable.
TRAFFIC_ARRAY_SPECS = {
    "peers": ("int16", ("V", "N", "F")),
    "code": ("int8", ("V", "N", "F")),
    "dist": ("int16", ("V", "N")),
    "first_src": ("int16", ("V", "N")),
    "failed": ("bool", ("N",)),
    "active": ("int16", ("N", "S")),
    "pruned": ("bool", ("V", "N", "S")),
    "prune_src": ("int16", ("V", "P")),
    "prune_dst": ("int16", ("V", "P")),
    "value_id": ("int32", ("V",)),
    "value_origin": ("int16", ("V",)),
    "prunes_total": ("int32", ("V",)),
}

#: v4 adaptive arrays (adaptive.py), present when the manifest's
#: ``gossip_mode`` is "adaptive".  Single-origin traces carry the
#: per-round direction bit; traffic traces the per-value phase bit plus
#: the per-node rescue deliveries (hop, -1 = no rescue) that make every
#: rescue attributable to its value slot (stats/edges.py).
ADAPTIVE_ARRAY_SPECS = {
    "adaptive_on": ("int8", ()),
}
TRAFFIC_ADAPTIVE_ARRAY_SPECS = {
    "value_pull": ("int8", ("V",)),
    "pull_hop": ("int16", ("V", "N")),
}

#: every array name a non-traffic readable schema can carry
ALL_ARRAY_SPECS = {**ARRAY_SPECS, **PULL_ARRAY_SPECS,
                   **ADAPTIVE_ARRAY_SPECS}
#: every array name a traffic readable schema can carry
ALL_TRAFFIC_ARRAY_SPECS = {**TRAFFIC_ARRAY_SPECS,
                           **TRAFFIC_ADAPTIVE_ARRAY_SPECS}


def specs_for_manifest(manifest: dict) -> dict:
    """The array-spec dict a manifest's schema/mode implies (v1 manifests
    and v2 push-mode manifests carry the base arrays only; v3+ traffic
    manifests — ``traffic_slots`` > 0 — the traffic arrays; v4 adaptive
    manifests additionally the switch-event arrays)."""
    if int(manifest.get("traffic_slots") or 0) > 0:
        return {name: ALL_TRAFFIC_ARRAY_SPECS[name]
                for name in (manifest.get("arrays") or TRAFFIC_ARRAY_SPECS)
                if name in ALL_TRAFFIC_ARRAY_SPECS}
    return {name: ALL_ARRAY_SPECS[name]
            for name in (manifest.get("arrays") or ARRAY_SPECS)
            if name in ALL_ARRAY_SPECS}


#: engine row name -> segment array name (detail + trace rows, cli harvest)
_ENGINE_ROW_MAP = {
    "trace_peers": "peers",
    "trace_code": "code",
    "dist": "dist",
    "trace_first": "first_src",
    "failed_mask": "failed",
    "trace_rot": "rot",
    "trace_active": "active",
    "trace_pruned": "pruned",
    "trace_prune_src": "prune_src",
    "trace_prune_dst": "prune_dst",
    "coverage": "coverage",
    "prunes_sent": "prunes_total",
}

#: engine trace rows -> v2 pull arrays (only emitted under pull modes)
_ENGINE_PULL_ROW_MAP = {
    "trace_pull_peers": "pull_peers",
    "trace_pull_code": "pull_code",
    "pull_hop": "pull_hop",
}

#: traffic-engine trace rows (engine/traffic.py) -> v3 traffic arrays
_TRAFFIC_ENGINE_ROW_MAP = {
    "trace_peers": "peers",
    "trace_code": "code",
    "t_hop": "dist",
    "trace_first": "first_src",
    "trace_failed": "failed",
    "trace_active": "active",
    "trace_pruned": "pruned",
    "trace_prune_src": "prune_src",
    "trace_prune_dst": "prune_dst",
    "trace_vid": "value_id",
    "trace_origin": "value_origin",
    "trace_prunes": "prunes_total",
}

_MATCH_KEYS = ("schema", "backend", "num_nodes", "push_fanout",
               "active_set_size", "prune_cap", "seed", "origins",
               "gossip_mode", "pull_slots", "traffic_slots")


#: adaptive engine trace rows (mode "adaptive") -> v4 arrays
_ENGINE_ADAPTIVE_ROW_MAP = {
    "adaptive_pull_active": "adaptive_on",
}
_TRAFFIC_ADAPTIVE_ROW_MAP = {
    "trace_value_pull": "value_pull",
    "trace_pull_hop": "pull_hop",
}


def block_from_engine_rows(rows) -> dict:
    """Engine harvest rows (numpy, ``[R, O, ...]``) -> writer block dict.
    Pull-phase and adaptive rows ride along when the engine emitted them
    (pull / adaptive modes)."""
    block = {seg: np.asarray(rows[eng])
             for eng, seg in _ENGINE_ROW_MAP.items()}
    for rowmap in (_ENGINE_PULL_ROW_MAP, _ENGINE_ADAPTIVE_ROW_MAP):
        for eng, seg in rowmap.items():
            if eng in rows:
                block[seg] = np.asarray(rows[eng])
    return block


def traffic_block_from_engine_rows(rows) -> dict:
    """Traffic-engine harvest rows (numpy, ``[R, V, ...]``) -> writer
    block dict for a ``traffic_slots > 0`` (v3+) trace; the v4 adaptive
    arrays ride along under gossip_mode "adaptive"."""
    block = {seg: np.asarray(rows[eng])
             for eng, seg in _TRAFFIC_ENGINE_ROW_MAP.items()}
    for eng, seg in _TRAFFIC_ADAPTIVE_ROW_MAP.items():
        if eng in rows:
            block[seg] = np.asarray(rows[eng])
    return block


def _atomic_write_bytes(path: str, payload: bytes) -> None:
    fd, tmp = tempfile.mkstemp(prefix=".trace-",
                               dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _atomic_savez(path: str, arrays: dict) -> int:
    fd, tmp = tempfile.mkstemp(suffix=".npz", prefix=".trace-",
                               dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        size = os.path.getsize(tmp)
        os.replace(tmp, path)
        return size
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class TraceWriter:
    """Incremental flight-recorder writer: one ``.npz`` segment per harvest
    block, one merged ``manifest.json`` (updated after every segment so a
    killed run still leaves a loadable trace).

    On construction against a directory that already holds a manifest for
    the *same* run geometry (num_nodes, fanout, seed, origins, backend, ...)
    existing segments are kept and new ones merged in — the ``--resume``
    composition contract: a checkpoint restart appends the remaining rounds
    without duplicating or losing already-traced ones.  A mismatched
    manifest is replaced (with a warning).
    """

    #: node ids are stored int16; the engine's MAX_NODES shares this bound,
    #: but the oracle backend has no intrinsic cap, so the writer enforces it
    MAX_TRACE_NODES = 32767

    def __init__(self, trace_dir: str, *, backend: str, num_nodes: int,
                 push_fanout: int, active_set_size: int, prune_cap: int,
                 origins, origin_pubkeys, seed: int, warm_up_rounds: int,
                 iterations: int, config=None, gossip_mode: str = "push",
                 pull_slots: int = 0, traffic_slots: int = 0):
        if num_nodes > self.MAX_TRACE_NODES:
            raise ValueError(
                f"trace arrays store node ids as int16; num_nodes must be "
                f"<= {self.MAX_TRACE_NODES}, got {num_nodes}")
        self.trace_dir = trace_dir
        os.makedirs(trace_dir, exist_ok=True)
        if traffic_slots > 0:
            # v3 traffic mode: value-slot event arrays; there is no origin
            # column (values carry their own origins per round)
            self.array_specs = dict(TRAFFIC_ARRAY_SPECS)
            if gossip_mode == "adaptive":
                self.array_specs.update(TRAFFIC_ADAPTIVE_ARRAY_SPECS)
        else:
            self.array_specs = dict(ARRAY_SPECS)
            if gossip_mode != "push":
                self.array_specs.update(PULL_ARRAY_SPECS)
            if gossip_mode == "adaptive":
                self.array_specs.update(ADAPTIVE_ARRAY_SPECS)
        from ..traffic import TRAFFIC_CODE_NAMES
        self.manifest = {
            "schema": TRACE_SCHEMA,
            "run_report_schema": RUN_REPORT_SCHEMA,
            "backend": str(backend),
            "num_nodes": int(num_nodes),
            "push_fanout": int(push_fanout),
            "active_set_size": int(active_set_size),
            "prune_cap": int(prune_cap),
            "gossip_mode": str(gossip_mode),
            "pull_slots": int(pull_slots) if gossip_mode != "push" else 0,
            "traffic_slots": int(traffic_slots),
            "origins": [int(o) for o in origins],
            "origin_pubkeys": [str(p) for p in origin_pubkeys],
            "seed": int(seed),
            "warm_up_rounds": int(warm_up_rounds),
            "iterations": int(iterations),
            "codes": ({str(k): v for k, v in TRAFFIC_CODE_NAMES.items()}
                      if traffic_slots > 0 else
                      {str(k): v for k, v in TRACE_CODE_NAMES.items()}),
            "pull_codes": {str(k): v for k, v in PULL_CODE_NAMES.items()},
            "arrays": {name: {"dtype": dt, "dims": list(dims)}
                       for name, (dt, dims) in self.array_specs.items()},
            "config": config_dict(config) if config is not None else {},
            "segments": [],
        }
        prior = self._load_existing_manifest()
        if prior is not None:
            self.manifest["segments"] = prior.get("segments", [])

    # -- resume merge -----------------------------------------------------

    def _load_existing_manifest(self):
        path = os.path.join(self.trace_dir, MANIFEST_NAME)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                prior = json.load(f)
        except (OSError, ValueError) as e:
            log.warning("WARNING: unreadable trace manifest %s (%s); "
                        "starting a fresh trace", path, e)
            return None
        mismatch = [k for k in _MATCH_KEYS
                    if prior.get(k) != self.manifest.get(k)]
        if mismatch:
            log.warning("WARNING: existing trace in %s was recorded under a "
                        "different run (%s differ); replacing it",
                        self.trace_dir, ", ".join(mismatch))
            return None
        log.info("trace: resuming into %s (%s prior segment(s) kept)",
                 self.trace_dir, len(prior.get("segments", [])))
        return prior

    # -- segments ---------------------------------------------------------

    def add_block(self, start_round: int, block: dict) -> dict:
        """Write one harvest block (arrays ``[R, O, ...]``) as a segment.

        Returns a summary dict: file, round range, delivered-edge / prune
        counts, bytes written, and the rounds whose prune capture hit the
        ``prune_cap`` truncation ceiling.
        """
        n_rounds = None
        out = {}
        for name, (dtype, _) in self.array_specs.items():
            if name not in block:
                raise ValueError(f"trace block missing array: {name}")
            arr = np.asarray(block[name])
            if n_rounds is None:
                n_rounds = arr.shape[0]
            elif arr.shape[0] != n_rounds:
                raise ValueError(
                    f"trace block round-axis mismatch for {name}: "
                    f"{arr.shape[0]} != {n_rounds}")
            out[name] = arr.astype(np.dtype(dtype), copy=False)
        start = int(start_round)
        end = start + int(n_rounds)
        out["rounds"] = np.arange(start, end, dtype=np.int32)

        delivered = int(np.count_nonzero(
            (out["code"] == TRACE_CANDIDATE)
            & (out["dist"] >= 0)[..., None]))
        captured_pairs = np.count_nonzero(out["prune_src"] >= 0,
                                          axis=-1)                 # [R, O]
        total_prunes = out["prunes_total"]
        truncated = sorted(
            int(out["rounds"][t])
            for t in range(n_rounds)
            if (total_prunes[t] > captured_pairs[t]).any())
        if truncated:
            log.warning("WARNING: trace prune capture truncated at "
                        "prune_cap=%s in round(s) %s — raise "
                        "EngineParams.trace_prune_cap for full prune "
                        "lineage", self.manifest["prune_cap"], truncated)

        fname = f"seg-{start:06d}-{end:06d}.npz"
        size = _atomic_savez(os.path.join(self.trace_dir, fname), out)
        summary = {
            "file": fname,
            "start_round": start,
            "end_round": end,
            "delivered_edges": delivered,
            "prunes": int(total_prunes.sum()),
            "truncated_prune_rounds": truncated,
            "bytes": size,
        }
        self._merge_segment(summary)
        self._write_manifest()
        return summary

    def _merge_segment(self, summary: dict) -> None:
        """Replace any existing segment overlapping the new round range
        (a resume re-running the same block overwrites it bit-identically;
        partially-overlapping stale segments are dropped, never doubled)."""
        s, e = summary["start_round"], summary["end_round"]
        kept = []
        for seg in self.manifest["segments"]:
            if seg["start_round"] < e and s < seg["end_round"]:
                if (seg["start_round"], seg["end_round"]) != (s, e):
                    log.warning("trace: dropping stale overlapping segment "
                                "%s", seg["file"])
                    try:
                        os.unlink(os.path.join(self.trace_dir, seg["file"]))
                    except OSError:
                        pass
                continue
            kept.append(seg)
        kept.append(summary)
        kept.sort(key=lambda g: g["start_round"])
        self.manifest["segments"] = kept

    def _write_manifest(self) -> None:
        payload = (json.dumps(self.manifest, indent=2) + "\n").encode()
        _atomic_write_bytes(os.path.join(self.trace_dir, MANIFEST_NAME),
                            payload)

    def finalize(self) -> dict:
        """Final manifest write; returns the manifest dict."""
        self._write_manifest()
        segs = self.manifest["segments"]
        rounds = sum(g["end_round"] - g["start_round"] for g in segs)
        log.info("trace: %s segment(s), %s round(s) in %s", len(segs),
                 rounds, self.trace_dir)
        return self.manifest


# --------------------------------------------------------------------------
# oracle-side collector
# --------------------------------------------------------------------------

class OracleTraceCollector:
    """Build engine-shaped trace blocks from the CPU oracle's per-round
    state (``oracle/cluster.py``).

    Divergences vs the engine capture, both documented here and visible in
    the manifest ``backend`` field: the oracle only *attempts* pushes from
    reached nodes, so ``peers``/``code`` rows of unreached sources stay
    empty (the engine records every node's candidate slots); and its
    rotation re-samples whole entries, so ``rot`` is a 0/1 event flag, not
    a rotated-in peer id.  ``first_src``, ``dist``, delivered edges, prune
    pairs and the active/pruned snapshots are definitionally identical —
    that is the bit-parity surface tests/test_trace.py locks down.
    """

    def __init__(self, index, origin_pubkey, *, push_fanout: int,
                 active_set_size: int, prune_cap: int,
                 gossip_mode: str = "push", pull_slots: int = 0):
        self.index = index
        self.origin_pk = origin_pubkey
        self.origin_idx = index.index_of(origin_pubkey)
        self.F = int(push_fanout)
        self.S = int(active_set_size)
        self.P = int(prune_cap)
        self.N = len(index)
        self.gossip_mode = str(gossip_mode)
        self.Q = int(pull_slots)
        self.array_specs = dict(ARRAY_SPECS)
        if self.gossip_mode != "push":
            self.array_specs.update(PULL_ARRAY_SPECS)
        if self.gossip_mode == "adaptive":
            self.array_specs.update(ADAPTIVE_ARRAY_SPECS)
        self._pre = None
        self._rounds = []     # [(round, {name: [O=1, ...] array})]
        #: adaptive mode: the CLI sets this per round to the direction bit
        #: in effect BEFORE the round's switch update (the engine's
        #: adaptive_pull_active row)
        self.adaptive_on = False

    def begin_round(self, cluster, node_map) -> None:
        """PRE-round snapshot (active sets + pruned bits as verb 1 will see
        them) and arm the cluster's edge log for this round."""
        from ..identity import get_stake_bucket

        N, S = self.N, self.S
        active = np.full((N, S), -1, np.int16)
        pruned = np.zeros((N, S), bool)
        origin_stake = node_map[self.origin_pk].stake
        for i, pk in enumerate(self.index.pubkeys):
            node = node_map[pk]
            bucket = get_stake_bucket(min(node.stake, origin_stake))
            entry = node.active_set.entries[bucket]
            for s, (peer, filt) in enumerate(entry.peers.items()):
                if s >= S:
                    break
                active[i, s] = self.index.index_of(peer)
                pruned[i, s] = self.origin_pk in filt
        self._pre = (active, pruned)
        cluster.edge_log = []

    def end_round(self, it: int, cluster, node_map, rotated_pks) -> None:
        """Collect the round's events after verbs 1-5 ran."""
        from ..constants import UNREACHED

        N, F, P = self.N, self.F, self.P
        idx_of = self.index.index_of
        active, pruned = self._pre
        self._pre = None

        peers = np.full((N, F), -1, np.int16)
        code = np.zeros((N, F), np.int8)
        slot_fill = np.zeros(N, np.int32)
        for src_pk, dst_pk, c in cluster.edge_log or ():
            si = idx_of(src_pk)
            k = slot_fill[si]
            if k < F:
                peers[si, k] = idx_of(dst_pk)
                code[si, k] = c
            slot_fill[si] += 1
        cluster.edge_log = None

        dist = np.full(N, -1, np.int16)
        for pk, d in cluster.distances.items():
            if d != UNREACHED:
                dist[idx_of(pk)] = d

        first = np.full(N, -1, np.int16)
        for dst_pk, srcs in cluster.orders.items():
            best = min((hops, idx_of(src_pk))
                       for src_pk, hops in srcs.items())
            first[idx_of(dst_pk)] = best[1]

        prune_src = np.full(P, -1, np.int16)
        prune_dst = np.full(P, -1, np.int16)
        total_prunes = 0
        k = 0
        for pruner_pk, prunes in cluster.prunes.items():
            for prunee_pk, origins_list in prunes.items():
                total_prunes += len(origins_list)
                if k < P:
                    prune_src[k] = idx_of(pruner_pk)
                    prune_dst[k] = idx_of(prunee_pk)
                    k += 1

        failed = np.array([node_map[pk].failed for pk in self.index.pubkeys],
                          dtype=bool)
        rot = np.full(N, -1, np.int16)
        for pk in rotated_pks or ():
            rot[idx_of(pk)] = 1

        row = {
            "peers": peers, "code": code, "dist": dist, "first_src": first,
            "failed": failed, "rot": rot, "active": active, "pruned": pruned,
            "prune_src": prune_src, "prune_dst": prune_dst,
            "coverage": np.float32((len(cluster.visited)
                                    + (len(cluster.pull.rescued)
                                       if cluster.pull is not None else 0))
                                   / N),
            "prunes_total": np.int32(total_prunes),
        }
        if self.gossip_mode != "push":
            # pull-phase capture (pull.py): the PullRound already carries
            # the engine-shaped per-slot arrays
            pr = cluster.pull
            if pr is not None:
                row["pull_peers"] = pr.peers
                row["pull_code"] = pr.code
                row["pull_hop"] = pr.pull_hop
            else:
                row["pull_peers"] = np.full((N, self.Q), -1, np.int16)
                row["pull_code"] = np.zeros((N, self.Q), np.int8)
                row["pull_hop"] = np.full(N, -1, np.int16)
        if self.gossip_mode == "adaptive":
            row["adaptive_on"] = np.int8(1 if self.adaptive_on else 0)
        self._rounds.append((int(it), row))

    def flush(self):
        """-> (start_round, block arrays ``[R, 1, ...]``) or None if empty.
        Collected rounds must be contiguous (they are: one per iteration)."""
        if not self._rounds:
            return None
        start = self._rounds[0][0]
        block = {
            name: np.stack([row[name] for _, row in self._rounds])[:, None]
            for name in self.array_specs
        }
        self._rounds = []
        return start, block


# --------------------------------------------------------------------------
# loading + validation
# --------------------------------------------------------------------------

class Trace:
    """A loaded trace: manifest + segment arrays concatenated on the round
    axis (``rounds[t]`` is the absolute round index of slice ``t``)."""

    def __init__(self, manifest: dict, rounds: np.ndarray, arrays: dict,
                 gaps=None):
        self.manifest = manifest
        self.rounds = rounds
        self.arrays = arrays
        self.gaps = list(gaps or [])

    def __len__(self):
        return int(self.rounds.shape[0])

    @property
    def num_nodes(self) -> int:
        return int(self.manifest["num_nodes"])

    @property
    def origins(self) -> list:
        return list(self.manifest["origins"])

    def col_of(self, origin: int) -> int:
        """Column index of an origin node id."""
        return self.origins.index(int(origin))

    def pos_of(self, round_idx: int) -> int:
        """Round-axis position of an absolute round index."""
        t = int(np.searchsorted(self.rounds, round_idx))
        if t >= len(self) or self.rounds[t] != round_idx:
            raise KeyError(f"round {round_idx} not in trace "
                           f"(have {self.rounds[0]}..{self.rounds[-1]})")
        return t

    def at(self, round_idx: int) -> dict:
        """All arrays for one absolute round: ``{name: [O, ...]}``."""
        t = self.pos_of(round_idx)
        return {name: arr[t] for name, arr in self.arrays.items()}


def load_trace(trace_dir: str) -> Trace:
    """Read ``manifest.json`` + every listed segment; concatenate on the
    round axis.  Raises on a missing/invalid manifest or segment; round
    gaps (e.g. a crashed run that never resumed) load fine and are listed
    in ``Trace.gaps``."""
    path = os.path.join(trace_dir, MANIFEST_NAME)
    with open(path) as f:
        manifest = json.load(f)
    problems = validate_trace_manifest(manifest)
    if problems:
        raise ValueError(f"invalid trace manifest {path}: {problems}")
    segs = sorted(manifest["segments"], key=lambda g: g["start_round"])
    if not segs:
        raise ValueError(f"trace {trace_dir} has no segments")
    specs = specs_for_manifest(manifest)
    rounds_parts, parts = [], {name: [] for name in specs}
    gaps = []
    prev_end = None
    for seg in segs:
        with np.load(os.path.join(trace_dir, seg["file"])) as z:
            rounds_parts.append(z["rounds"])
            for name in specs:
                parts[name].append(z[name])
        if prev_end is not None and seg["start_round"] != prev_end:
            gaps.append((prev_end, seg["start_round"]))
        prev_end = seg["end_round"]
    if gaps:
        log.warning("WARNING: trace %s has round gap(s): %s", trace_dir,
                    gaps)
    rounds = np.concatenate(rounds_parts)
    arrays = {name: np.concatenate(parts[name]) for name in specs}
    return Trace(manifest, rounds, arrays, gaps=gaps)


def validate_trace_manifest(manifest: dict) -> list:
    """Schema self-check: returns a list of problems (empty == valid)."""
    problems = []
    if not isinstance(manifest, dict):
        return [f"manifest is {type(manifest).__name__}, not dict"]
    if manifest.get("schema") not in READABLE_SCHEMAS:
        problems.append(f"unknown schema: {manifest.get('schema')!r}")
    for key, types in (("backend", str), ("num_nodes", int),
                       ("push_fanout", int), ("active_set_size", int),
                       ("prune_cap", int), ("origins", list),
                       ("origin_pubkeys", list), ("seed", int),
                       ("warm_up_rounds", int), ("iterations", int),
                       ("arrays", dict), ("segments", list),
                       ("config", dict)):
        if not isinstance(manifest.get(key), types):
            problems.append(f"key {key}: missing or not {types.__name__}")
    is_traffic = int(manifest.get("traffic_slots") or 0) > 0
    base_specs = TRAFFIC_ARRAY_SPECS if is_traffic else ARRAY_SPECS
    for name in base_specs:
        if name not in (manifest.get("arrays") or {}):
            problems.append(f"arrays entry missing: {name}")
    if manifest.get("schema") in (TRACE_SCHEMA_V2, TRACE_SCHEMA_V3,
                                  TRACE_SCHEMA):
        # v2+: mode + pull geometry are mandatory; pull arrays exist
        # exactly when the mode has a pull phase
        mode = manifest.get("gossip_mode")
        if mode not in ("push", "pull", "push-pull", "adaptive"):
            problems.append(f"v2 manifest: bad gossip_mode {mode!r}")
        if not isinstance(manifest.get("pull_slots"), int):
            problems.append("v2 manifest: pull_slots missing or not int")
        if mode in ("pull", "push-pull", "adaptive") and not is_traffic:
            for name in PULL_ARRAY_SPECS:
                if name not in (manifest.get("arrays") or {}):
                    problems.append(f"pull arrays entry missing: {name}")
    if (manifest.get("schema") in (TRACE_SCHEMA_V3, TRACE_SCHEMA)
            and is_traffic):
        # v3+ traffic manifests: the value-id column is mandatory
        for name in ("value_id", "value_origin"):
            if name not in (manifest.get("arrays") or {}):
                problems.append(f"traffic arrays entry missing: {name}")
    if manifest.get("schema") == TRACE_SCHEMA:
        # v4: adaptive manifests carry the switch-event arrays
        if manifest.get("gossip_mode") == "adaptive":
            need = (TRAFFIC_ADAPTIVE_ARRAY_SPECS if is_traffic
                    else ADAPTIVE_ARRAY_SPECS)
            for name in need:
                if name not in (manifest.get("arrays") or {}):
                    problems.append(
                        f"adaptive arrays entry missing: {name}")
    for seg in manifest.get("segments") or []:
        if (not isinstance(seg, dict) or "file" not in seg
                or "start_round" not in seg or "end_round" not in seg):
            problems.append(f"malformed segment entry: {seg!r}")
        elif seg["end_round"] <= seg["start_round"]:
            problems.append(f"empty/negative segment range: {seg['file']}")
    if (isinstance(manifest.get("origins"), list)
            and isinstance(manifest.get("origin_pubkeys"), list)
            and len(manifest["origins"]) != len(manifest["origin_pubkeys"])):
        problems.append("origins / origin_pubkeys length mismatch")
    try:
        json.dumps(manifest)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    return problems


def validate_trace_dir(trace_dir: str) -> list:
    """Manifest validation + on-disk segment existence/shape checks."""
    path = os.path.join(trace_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return [f"no {MANIFEST_NAME} in {trace_dir}"]
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable manifest: {e}"]
    problems = validate_trace_manifest(manifest)
    if problems:
        return problems
    n, f_, s, p = (manifest["num_nodes"], manifest["push_fanout"],
                   manifest["active_set_size"], manifest["prune_cap"])
    o = len(manifest["origins"])
    dim = {"N": n, "F": f_, "S": s, "P": p,
           "Q": manifest.get("pull_slots", 0),
           "V": manifest.get("traffic_slots", 0)}
    specs = specs_for_manifest(manifest)
    for seg in manifest["segments"]:
        fpath = os.path.join(trace_dir, seg["file"])
        if not os.path.exists(fpath):
            problems.append(f"segment file missing: {seg['file']}")
            continue
        r = seg["end_round"] - seg["start_round"]
        with np.load(fpath) as z:
            names = set(z.files)
            for name, (dtype, dims) in specs.items():
                if name not in names:
                    problems.append(f"{seg['file']}: missing array {name}")
                    continue
                # traffic (v3) arrays carry their own V axis in ``dims``
                # instead of the per-origin column
                want = ((r,) if dim["V"] > 0
                        else (r, o)) + tuple(dim[d] for d in dims)
                if z[name].shape != want:
                    problems.append(
                        f"{seg['file']}: {name} shape {z[name].shape} != "
                        f"{want}")
                if z[name].dtype != np.dtype(dtype):
                    problems.append(
                        f"{seg['file']}: {name} dtype {z[name].dtype} != "
                        f"{dtype}")
            if "rounds" not in names:
                problems.append(f"{seg['file']}: missing rounds axis")
    return problems
