"""Hierarchical span timers + counters: the host-side telemetry core.

Design constraints (ISSUE 2):

* **low overhead** — one ``time.perf_counter()`` pair and one locked dict
  update per span exit (~1-2 us); cheap enough to leave on in production
  paths, and a ``enabled=False`` registry short-circuits to a shared no-op
  context manager for the zero-cost path.
* **nestable** — spans are reentrant; a span opened inside another span
  (same thread, same or different name) records its own wall time
  independently.  Hierarchy is expressed through slash-separated names
  (``engine/rounds``, ``stats/harvest``), the same convention XProf uses
  for ``jax.named_scope`` stages, so host spans and device traces line up.
* **thread-safe** — the Influx sender thread and heartbeat callers may
  record concurrently with the simulation thread; the active-span stack is
  thread-local and all aggregate updates take the registry lock.

The module-level default registry is what the CLI, bench.py and the tools
share (one process == one run); tests construct private registries.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class _NullSpan:
    """Shared no-op context manager for disabled registries."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class SpanRegistry:
    """Aggregating span-timer + counter + run-metadata registry."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._spans: dict[str, list] = {}     # name -> [total_s, count]
        self._counters: dict[str, float] = {}
        self._info: dict[str, object] = {}
        self._start = time.perf_counter()

    # -- spans ------------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextmanager
    def _span_cm(self, name: str):
        stack = self._stack()
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                ent = self._spans.get(name)
                if ent is None:
                    self._spans[name] = [dt, 1]
                else:
                    ent[0] += dt
                    ent[1] += 1

    def span(self, name: str):
        """Context manager timing a named span (reentrant, thread-safe)."""
        if not self.enabled:
            return _NULL_SPAN
        return self._span_cm(name)

    def record(self, name: str, seconds: float, count: int = 1) -> None:
        """Record an externally-measured duration under ``name`` (e.g. a
        differentially-derived compile time, obs/difftime.py)."""
        if not self.enabled:
            return
        with self._lock:
            ent = self._spans.get(name)
            if ent is None:
                self._spans[name] = [float(seconds), count]
            else:
                ent[0] += float(seconds)
                ent[1] += count

    def get(self, name: str) -> float:
        """Total seconds recorded under ``name`` (0.0 if never entered)."""
        with self._lock:
            ent = self._spans.get(name)
            return ent[0] if ent else 0.0

    def count(self, name: str) -> int:
        with self._lock:
            ent = self._spans.get(name)
            return ent[1] if ent else 0

    def active_depth(self) -> int:
        """Current nesting depth on the calling thread (diagnostics)."""
        return len(self._stack())

    # -- counters ---------------------------------------------------------

    def add(self, name: str, value: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    # -- run metadata -----------------------------------------------------

    def set_info(self, key: str, value) -> None:
        with self._lock:
            self._info[key] = value

    def info(self, key: str, default=None):
        with self._lock:
            return self._info.get(key, default)

    # -- lifecycle --------------------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time copy: ``{"spans": {name: {total_s, count}},
        "counters": {...}, "info": {...}, "wall_s": ...}``."""
        with self._lock:
            return {
                "spans": {k: {"total_s": v[0], "count": v[1]}
                          for k, v in sorted(self._spans.items())},
                "counters": dict(sorted(self._counters.items())),
                "info": dict(self._info),
                "wall_s": time.perf_counter() - self._start,
            }

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._counters.clear()
            self._info.clear()
            self._start = time.perf_counter()


_DEFAULT = SpanRegistry()


def get_registry() -> SpanRegistry:
    """The process-wide default registry (one process == one run)."""
    return _DEFAULT


def span(name: str):
    """``with obs.span("engine/rounds"): ...`` on the default registry."""
    return _DEFAULT.span(name)
