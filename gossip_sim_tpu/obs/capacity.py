"""Capacity observatory: exact memory attribution + XLA cost harvest.

ROADMAP item 1 (sparse engine for 100k-1M nodes) needs to *measure* the
dense per-node tables it is refactoring away — until this module, the
repo's memory story was "run it and watch for the OOM".  Three layers,
all host-side and JAX-free at import (the bench parent-process contract):

1. **Static capacity ledger** — walks the exact array inventory of the
   carried pytrees (:class:`SimState`, :class:`TrafficState`,
   :class:`EngineKnobs`, the flight-recorder trace rows, the static
   cluster tables) and emits per-array byte attribution as closed-form
   functions of ``(N, S, M, lanes, trace caps)``.  The totals are
   *bit-exact* against live device buffers: for every supported config,
   ``predict_sim_state_bytes(params, O) == sum(x.nbytes for x in state)``
   (tests/test_capacity.py, tools/capacity_smoke.py).  Every term whose
   bytes grow quadratically in N under the run's interpretation (the
   origin axis tracks N in ``--all-origins`` mode) is flagged — those are
   exactly the dense tables blocking web scale (FS_GPlib, PAPERS.md).

2. **XLA cost harvest** — captures ``compiled.cost_analysis()`` and
   ``compiled.memory_analysis()`` (FLOPs, transcendentals, argument /
   output / temp / generated-code bytes) for the engine executables.  The
   harvest is keyed by compile-cache entry (site label + static key +
   abstract arg specs + dispatch epoch), so warm calls reuse the harvest
   for free.  Harvesting a NEW entry pays one extra XLA compile (JAX's
   AOT ``lower().compile()`` does not share the jit execution cache), so
   it is **opt-in** (``--capacity-harvest`` / :func:`set_harvest_enabled`)
   and pairs well with the persistent compilation cache
   (``--compilation-cache-dir``), which turns the second compile into a
   disk hit.  The resilience supervisor bumps the dispatch epoch on
   retries/CPU-fallback so re-dispatched units re-harvest against the
   executable they actually ran (resilience.py).

3. **Planning queries** — :func:`fit_budget` (largest N that fits a byte
   budget, exact ledger arithmetic, no device needed) and N-projection
   via re-evaluating the ledger at hypothetical N — the closed forms make
   extrapolation exact, which is what ``tools/capacity_report.py`` builds
   its ROADMAP-item-1 evidence tables from.

Nothing here touches simulation state: enabling the ledger, the harvest
or the memwatch sampler has zero bit-impact on stats parity snapshots
and Influx wire lines (tools/capacity_smoke.py enforces this).
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

import numpy as np

from .spans import get_registry

CAPACITY_SCHEMA = "gossip-sim-tpu/capacity-ledger/v1"

#: stake-bucket class count (sampler/pull tables width)
_NB = 25

#: default trace harvest block (cli.HARVEST_BLOCK; kept in sync by a test)
TRACE_BLOCK_ROUNDS = 256

_DTYPE_BYTES = {"bool": 1, "int32": 4, "uint32": 4, "int64": 8,
                "uint64": 8, "float32": 4, "float64": 8}


class LedgerEntry(NamedTuple):
    """One array's closed-form byte attribution."""

    name: str       # pytree field (dotted path for nested containers)
    group: str      # subsystem: active-set | received-cache |
                    # traffic-planes | stats | pull | adaptive | health |
                    # core | tables | knobs | trace
    shape: tuple    # concrete shape at this config
    dtype: str
    bytes: int      # exact: prod(shape) * itemsize
    formula: str    # the closed form, e.g. "O*N*S*4"
    n_degree: int   # polynomial degree in N under this config's
                    # interpretation (the O axis counts when
                    # origins_scale_with_n); >= 2 == a dense web-scale
                    # blocker (the ROADMAP item 1 refactor targets)
    exact: bool = True  # False = workspace *estimate*, excluded from the
                        # bit-exact state totals and the parity tests

    def to_dict(self) -> dict:
        return {"name": self.name, "group": self.group,
                "shape": list(self.shape), "dtype": self.dtype,
                "bytes": int(self.bytes), "formula": self.formula,
                "n_degree": int(self.n_degree), "exact": bool(self.exact)}


def _entry(name, group, shape, dtype, formula, n_degree, exact=True):
    size = int(np.prod([int(s) for s in shape], dtype=np.int64)) if shape \
        else 1
    return LedgerEntry(name=name, group=group, shape=tuple(int(s) for s
                                                           in shape),
                       dtype=dtype, bytes=size * _DTYPE_BYTES[dtype],
                       formula=formula, n_degree=n_degree, exact=exact)


# --------------------------------------------------------------------------
# per-pytree inventories (must mirror the NamedTuple definitions exactly)
# --------------------------------------------------------------------------

def sim_state_entries(params, origin_batch: int = 1,
                      origins_scale_with_n: bool = False) -> list:
    """The exact array inventory of one :class:`SimState` with O origin
    columns (engine/core.py init_state — field order preserved).  ``sum
    of bytes`` equals ``sum(x.nbytes)`` of a live instance bit-exactly."""
    N, S, C, H = (params.num_nodes, params.active_set_size, params.rc_slots,
                  params.hist_bins)
    O = int(origin_batch)
    od = 1 if origins_scale_with_n else 0   # the O axis tracks N?
    sparse = getattr(params, "representation", "dense") == "sparse"
    e = _entry
    # Sparse representation (engine/sparse.py): the received-cache stake
    # planes are derived from ClusterTables each round, so the carried
    # arrays are zero-width [O, N, 0] — exactly 0 bytes, and the cache
    # entries move to the "sparse" ledger group so fit-budget projections
    # price the representation switch.
    rc_group = "sparse" if sparse else "received-cache"
    Cs = 0 if sparse else C
    rc_pf = "O*N*0*4 (derived: tables.shi/slo[rc_src])" if sparse \
        else "O*N*C*4"
    return [
        e("key", "core", (O, 2), "uint32", "O*2*4", od),
        e("active", "active-set", (O, N, S), "int32", "O*N*S*4", 1 + od),
        e("pruned", "active-set", (O, N, S), "bool", "O*N*S*1", 1 + od),
        e("tfail", "active-set", (O, N, S), "bool", "O*N*S*1", 1 + od),
        e("rc_src", rc_group, (O, N, C), "int32", "O*N*C*4", 1 + od),
        e("rc_score", rc_group, (O, N, C), "int32", "O*N*C*4",
          1 + od),
        e("rc_shi", rc_group, (O, N, Cs), "int32", rc_pf,
          od if sparse else 1 + od),
        e("rc_slo", rc_group, (O, N, Cs), "int32", rc_pf,
          od if sparse else 1 + od),
        e("rc_upserts", rc_group, (O, N), "int32", "O*N*4", 1 + od),
        e("failed", "core", (O, N), "bool", "O*N*1", 1 + od),
        e("egress_acc", "stats", (O, N), "int32", "O*N*4", 1 + od),
        e("ingress_acc", "stats", (O, N), "int32", "O*N*4", 1 + od),
        e("prune_acc", "stats", (O, N), "int32", "O*N*4", 1 + od),
        e("stranded_acc", "stats", (O, N), "int32", "O*N*4", 1 + od),
        e("hops_hist_acc", "stats", (O, H), "int32", "O*H*4", od),
        e("pull_hops_hist_acc", "pull", (O, H), "int32", "O*H*4", od),
        e("pull_rescued_acc", "pull", (O, N), "int32", "O*N*4", 1 + od),
        e("health_prune_recv", "health", (O, N), "int32", "O*N*4", 1 + od),
        e("health_first_round", "health", (O, N), "int32", "O*N*4", 1 + od),
        e("adaptive_pull_on", "adaptive", (O,), "bool", "O*1", od),
    ]


def traffic_state_entries(params) -> list:
    """The exact array inventory of one :class:`TrafficState`
    (engine/traffic.py init_traffic_state).  The value axis V is the
    static ``traffic_slots`` (M) — the per-value planes scale as M*N, the
    shared network as N alone."""
    static = params.static_part()
    V = static.traffic_slots
    if V <= 0:
        return []
    N, S, C = params.num_nodes, params.active_set_size, params.rc_slots
    e = _entry
    return [
        e("active", "active-set", (N, S), "int32", "N*S*4", 1),
        e("failed", "core", (N,), "bool", "N*1", 1),
        e("next_vid", "core", (), "int32", "4", 0),
        e("v_live", "traffic-planes", (V,), "bool", "M*1", 0),
        e("v_vid", "traffic-planes", (V,), "int32", "M*4", 0),
        e("v_origin", "traffic-planes", (V,), "int32", "M*4", 0),
        e("v_birth", "traffic-planes", (V,), "int32", "M*4", 0),
        e("v_stall", "traffic-planes", (V,), "int32", "M*4", 0),
        e("v_holder", "traffic-planes", (V, N), "bool", "M*N*1", 1),
        e("v_hop", "traffic-planes", (V, N), "int32", "M*N*4", 1),
        e("v_m", "traffic-planes", (V,), "int32", "M*4", 0),
        e("pruned", "active-set", (V, N, S), "bool", "M*N*S*1", 1),
        e("rc_src", "received-cache", (V, N, C), "int32", "M*N*C*4", 1),
        e("rc_score", "received-cache", (V, N, C), "int32", "M*N*C*4", 1),
        e("rc_shi", "received-cache", (V, N, C), "int32", "M*N*C*4", 1),
        e("rc_slo", "received-cache", (V, N, C), "int32", "M*N*C*4", 1),
        e("rc_upserts", "received-cache", (V, N), "int32", "M*N*4", 1),
        e("inj_acc", "stats", (), "int32", "4", 0),
        e("injdrop_acc", "stats", (), "int32", "4", 0),
        e("ret_acc", "stats", (), "int32", "4", 0),
        e("conv_acc", "stats", (), "int32", "4", 0),
        e("defer_acc", "stats", (N,), "int32", "N*4", 1),
        e("qdrop_acc", "stats", (N,), "int32", "N*4", 1),
        e("sent_acc", "stats", (N,), "int32", "N*4", 1),
        e("recv_acc", "stats", (N,), "int32", "N*4", 1),
        e("prune_acc", "stats", (N,), "int32", "N*4", 1),
        e("v_pull", "adaptive", (V,), "bool", "M*1", 0),
        e("v_rescued", "adaptive", (V,), "int32", "M*4", 0),
        e("v_qdrop", "adaptive", (V,), "int32", "M*4", 0),
        e("health_prune_recv", "health", (N,), "int32", "N*4", 1),
        e("health_lat_acc", "health", (N,), "int32", "N*4", 1),
        e("health_del_acc", "health", (N,), "int32", "N*4", 1),
        e("health_rescued_acc", "health", (N,), "int32", "N*4", 1),
    ]


def cluster_tables_entries(params,
                           origins_scale_with_n: bool = False) -> list:
    """Static per-cluster device tables (ClusterTables + SamplerTables)."""
    N = params.num_nodes
    e = _entry
    return [
        e("stakes", "tables", (N + 1,), "int64", "(N+1)*8", 1),
        e("buckets", "tables", (N,), "int32", "N*4", 1),
        e("sampler.perm", "tables", (N,), "int32", "N*4", 1),
        e("sampler.class_start", "tables", (_NB,), "int32", "NB*4", 0),
        e("sampler.class_count", "tables", (_NB,), "int32", "NB*4", 0),
        e("sampler.class_cdf", "tables", (_NB, _NB), "float32", "NB*NB*4",
          0),
        e("sampler.cdf_own", "tables", (N, _NB), "float32", "N*NB*4", 1),
        e("shi", "tables", (N + 1,), "int32", "(N+1)*4", 1),
        e("slo", "tables", (N + 1,), "int32", "(N+1)*4", 1),
        # np.concatenate([...i32, [0]]) promotes: the live array is i64
        e("side", "tables", (N + 1,), "int64", "(N+1)*8", 1),
        # node-health decile ids (obs/health.py digest segment ids)
        e("stake_decile", "tables", (N,), "int32", "N*4", 1),
    ]


def traffic_tables_entries(params) -> list:
    """TrafficTables (traffic.py): the shared top-entry class CDF."""
    if params.static_part().traffic_slots <= 0:
        return []
    N = params.num_nodes
    e = _entry
    return [
        e("traffic.perm", "tables", (N,), "int32", "N*4", 1),
        e("traffic.class_start", "tables", (_NB,), "int32", "NB*4", 0),
        e("traffic.class_count", "tables", (_NB,), "int32", "NB*4", 0),
        e("traffic.cdf", "tables", (_NB,), "float32", "NB*4", 0),
    ]


def knobs_entries() -> list:
    """:class:`EngineKnobs` — every traced scalar, exact per-leaf dtype
    bytes (the pytree the lane runner stacks into [K] leaves).  Dtypes
    are read off a canonical instance (params.py is JAX-free), so a new
    knob can never drift out of the ledger."""
    from ..engine.params import EngineParams
    kn = EngineParams(num_nodes=2).knob_values()
    out = []
    for field, value in zip(kn._fields, kn):
        dt = np.asarray(value).dtype
        out.append(_entry(f"knobs.{field}", "knobs", (), str(dt),
                          str(dt.itemsize), 0))
    return out


def trace_entries(params, origin_batch: int = 1,
                  rounds: int = TRACE_BLOCK_ROUNDS,
                  origins_scale_with_n: bool = False) -> list:
    """Flight-recorder capture rows per harvested block (obs/trace.py):
    the extra device outputs a ``trace=True`` round emits, times the
    ``rounds`` of one harvest block (cli.HARVEST_BLOCK).  This is the
    peak *device-side* trace footprint; the npz segments on disk compress
    it away."""
    N, S = params.num_nodes, params.active_set_size
    F = min(params.push_fanout, S)
    PC = params.prune_cap
    O, R = int(origin_batch), int(rounds)
    od = 1 if origins_scale_with_n else 0
    e = _entry
    out = [
        e("trace_peers", "trace", (R, O, N, F), "int32", "R*O*N*F*4",
          1 + od),
        e("trace_code", "trace", (R, O, N, F), "int32", "R*O*N*F*4", 1 + od),
        e("trace_first", "trace", (R, O, N), "int32", "R*O*N*4", 1 + od),
        e("trace_prune_src", "trace", (R, O, PC), "int32", "R*O*PC*4",
          1 + od),   # PC resolves to 16*N by default — N-linear
        e("trace_prune_dst", "trace", (R, O, PC), "int32", "R*O*PC*4",
          1 + od),
        e("trace_rot", "trace", (R, O, N), "int32", "R*O*N*4", 1 + od),
        e("trace_active", "trace", (R, O, N, S), "int32", "R*O*N*S*4",
          1 + od),
        e("trace_pruned", "trace", (R, O, N, S), "bool", "R*O*N*S*1",
          1 + od),
    ]
    if params.has_pull:
        PS = params.pull_slots_resolved
        out += [
            e("trace_pull_peers", "trace", (R, O, N, PS), "int32",
              "R*O*N*PS*4", 1 + od),
            e("trace_pull_code", "trace", (R, O, N, PS), "int32",
              "R*O*N*PS*4", 1 + od),
        ]
    return out


def workspace_entries(params, origin_batch: int = 1,
                      origins_scale_with_n: bool = False) -> list:
    """*Estimates* of the dominant per-round sort workspaces (the dense
    candidate/routing matrices engine/core.py materializes inside one
    round).  Not part of the bit-exact state totals (``exact=False``) —
    XLA's ``temp_size_in_bytes`` from the cost harvest is the measured
    ground truth — but they name the O(N*F)/O(N*K) intermediates that,
    multiplied by an N-wide origin axis, are the N^2 compute-side
    barrier the ROADMAP item 1 sparse refactor removes."""
    N, S = params.num_nodes, params.active_set_size
    F = min(params.push_fanout, S)
    K = params.k_inbound
    O = int(origin_batch)
    od = 1 if origins_scale_with_n else 0
    e = _entry
    return [
        e("round.push_edges", "workspace", (O, N, F), "int32",
          "O*N*F*4 (tgt/deliver candidates)", 1 + od, exact=False),
        e("round.bfs_sort_keys", "workspace", (O, N * F + N), "int32",
          "O*(N*F+N)*4 (frontier edge sort)", 1 + od, exact=False),
        e("round.inbound_rank", "workspace", (O, 2 * (N * F + N)), "int32",
          "O*2*(N*F+N)*4 (consume 4-key sort)", 1 + od, exact=False),
        e("round.inbound_rows", "workspace", (O, N, K), "int32",
          "O*N*K*4 (ranked inbound)", 1 + od, exact=False),
        e("round.rc_merge_rows", "workspace", (O, N, params.rc_slots + K),
          "int32", "O*N*(C+K)*4 (cache merge sort)", 1 + od, exact=False),
        e("round.prune_apply_keys", "workspace", (O, N * S), "int32",
          "O*N*S*4 (prune sort-join)", 1 + od, exact=False),
    ]


# --------------------------------------------------------------------------
# the assembled ledger
# --------------------------------------------------------------------------

def _scale_lanes(entries: list, lanes: int) -> list:
    """Prefix every entry with the lane axis K (engine/lanes.py
    broadcast_state tiles the whole state pytree per lane)."""
    K = int(lanes)
    return [ent._replace(shape=(K,) + ent.shape, bytes=ent.bytes * K,
                         formula=f"K*{ent.formula}")
            for ent in entries]


def capacity_ledger(params, *, origin_batch: int = 1, lanes: int = 0,
                    trace: bool = False,
                    trace_rounds: int = TRACE_BLOCK_ROUNDS,
                    origins_scale_with_n: bool = False,
                    include_workspace: bool = True) -> dict:
    """The full closed-form memory ledger for one engine configuration.

    ``origin_batch`` is the live O axis (1 for single runs, R for the
    origin-rank batch, the batch width for ``--all-origins``); ``lanes``
    > 0 multiplies the carried state by the lane axis K; ``trace`` adds
    the flight-recorder block rows; ``origins_scale_with_n`` marks the O
    axis as N-tracking for the dense-term flags (the all-origins /
    web-scale interpretation: simulating every origin makes every
    ``[O, N, ...]`` array O(N^2)).

    Returns a JSON-safe dict; the ``state_bytes`` total is bit-exact vs
    live donated buffers, ``total_bytes`` adds tables/knobs/trace, and
    workspace estimates ride along unsummed (``exact: false``)."""
    osn = bool(origins_scale_with_n)
    traffic_on = params.static_part().traffic_slots > 0
    if traffic_on:
        state = traffic_state_entries(params)
    else:
        state = sim_state_entries(params, origin_batch,
                                  origins_scale_with_n=osn)
    if lanes and lanes > 0:
        state = _scale_lanes(state, lanes)
    tables = (cluster_tables_entries(params, origins_scale_with_n=osn)
              + traffic_tables_entries(params))
    knobs = knobs_entries()
    if lanes and lanes > 0:
        knobs = _scale_lanes(knobs, lanes)
    # traffic-mode traces carry a value axis with their own caps
    # (engine/traffic.py); the ledger models the single-origin recorder
    trace_rows = (trace_entries(params, origin_batch, trace_rounds,
                                origins_scale_with_n=osn)
                  if trace and not traffic_on else [])
    entries = state + tables + knobs + trace_rows
    if include_workspace and not traffic_on:
        entries = entries + workspace_entries(
            params, origin_batch, origins_scale_with_n=osn)

    groups: dict = {}
    for ent in entries:
        if ent.exact:
            groups[ent.group] = groups.get(ent.group, 0) + ent.bytes
    state_bytes = sum(ent.bytes for ent in state)
    total = sum(ent.bytes for ent in entries if ent.exact)
    # exact entries only: the workspace rows are estimates excluded from
    # every total, so they must not be named as ledger dense terms either
    # (they keep their n_degree flag in `entries` for the report tool)
    dense = [ent for ent in entries if ent.n_degree >= 2 and ent.exact]
    N = params.num_nodes
    return {
        "schema": CAPACITY_SCHEMA,
        "num_nodes": int(N),
        "origin_batch": int(origin_batch),
        "lanes": int(lanes),
        "traffic_slots": int(params.static_part().traffic_slots),
        "gossip_mode": params.gossip_mode,
        "trace": bool(trace),
        "origins_scale_with_n": osn,
        "entries": [ent.to_dict() for ent in entries],
        "groups": {k: int(v) for k, v in sorted(groups.items())},
        "state_bytes": int(state_bytes),
        "total_bytes": int(total),
        "bytes_per_node": round(total / max(N, 1), 2),
        "state_bytes_per_node": round(state_bytes / max(N, 1), 2),
        "dense_terms": [ent.name for ent in dense],
        "dense_bytes": int(sum(ent.bytes for ent in dense)),
    }


def predict_sim_state_bytes(params, origin_batch: int = 1,
                            lanes: int = 0) -> int:
    """Exact total bytes of a live :class:`SimState` at this config —
    the parity contract with ``sum(x.nbytes for x in state)``."""
    entries = sim_state_entries(params, origin_batch)
    if lanes and lanes > 0:
        entries = _scale_lanes(entries, lanes)
    return sum(ent.bytes for ent in entries)


def predict_request_bytes(params, origins) -> int:
    """Price one serve/plan request before it touches the device.

    ``origins`` is the request's origin spec — either the origin index
    sequence itself or an int origin count; the request's device cost is
    the one ``[O, ...]`` SimState lane it will occupy.  JAX-free closed
    form shared by the serve admission controller (serve/admission.py)
    and tools/capacity_report.py, exact against live ``nbytes`` by the
    same contract as :func:`predict_sim_state_bytes`
    (tests/test_capacity.py)."""
    o = int(origins) if isinstance(origins, (int, float)) else len(origins)
    if o < 1:
        raise ValueError(f"request needs at least one origin (got {o})")
    return predict_sim_state_bytes(params, origin_batch=o)


def predict_traffic_state_bytes(params, lanes: int = 0) -> int:
    """Exact total bytes of a live :class:`TrafficState`."""
    entries = traffic_state_entries(params)
    if lanes and lanes > 0:
        entries = _scale_lanes(entries, lanes)
    return sum(ent.bytes for ent in entries)


def measure_pytree(tree) -> tuple:
    """(total_nbytes, [(leaf_path, shape, dtype, nbytes), ...]) of a live
    pytree — the other arm of the exactness checks."""
    import jax
    leaves, _ = jax.tree_util.tree_flatten(tree)
    rows = []
    total = 0
    for i, leaf in enumerate(leaves):
        nb = int(getattr(leaf, "nbytes", 0))
        rows.append((f"leaf{i}", tuple(getattr(leaf, "shape", ())),
                     str(getattr(leaf, "dtype", "?")), nb))
        total += nb
    return total, rows


# --------------------------------------------------------------------------
# planning queries
# --------------------------------------------------------------------------

_SIZE_SUFFIXES = {"k": 10 ** 3, "m": 10 ** 6, "g": 10 ** 9, "t": 10 ** 12,
                  "kb": 2 ** 10, "mb": 2 ** 20, "gb": 2 ** 30,
                  "tb": 2 ** 40, "kib": 2 ** 10, "mib": 2 ** 20,
                  "gib": 2 ** 30, "tib": 2 ** 40, "b": 1}


def parse_size(text) -> int:
    """'16GB' / '512MiB' / '2e9' -> bytes (binary units for the *B forms,
    matching accelerator HBM marketing... which is what budgets quote)."""
    if isinstance(text, (int, float)):
        return int(text)
    s = str(text).strip().lower().replace(" ", "")
    for suf in sorted(_SIZE_SUFFIXES, key=len, reverse=True):
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * _SIZE_SUFFIXES[suf])
    return int(float(s))


def ledger_total_at(params, n: int, *, origin_batch=None, lanes: int = 0,
                    trace: bool = False,
                    origins_scale_with_n: bool = False) -> int:
    """Exact ledger total re-evaluated at a hypothetical node count
    ``n`` (the closed forms make this pure arithmetic — no device, no
    MAX_NODES cap).  ``origin_batch=None`` keeps the configured batch;
    with ``origins_scale_with_n`` the O axis is set to ``n`` itself (the
    all-origins interpretation)."""
    p = params._replace(num_nodes=int(n))
    ob = int(n) if origins_scale_with_n else int(origin_batch or 1)
    led = capacity_ledger(p, origin_batch=ob, lanes=lanes, trace=trace,
                          origins_scale_with_n=origins_scale_with_n,
                          include_workspace=False)
    return led["total_bytes"]


def fit_budget(params, budget_bytes: int, *, origin_batch: int = 1,
               lanes: int = 0, trace: bool = False,
               origins_scale_with_n: bool = False,
               n_max: int = 1 << 30) -> int:
    """Largest N whose exact ledger total fits ``budget_bytes`` (binary
    search over the closed forms; 0 when even N=2 does not fit)."""
    kw = dict(origin_batch=origin_batch, lanes=lanes, trace=trace,
              origins_scale_with_n=origins_scale_with_n)
    if ledger_total_at(params, 2, **kw) > budget_bytes:
        return 0
    lo, hi = 2, 4
    while hi < n_max and ledger_total_at(params, hi, **kw) <= budget_bytes:
        lo, hi = hi, hi * 2
    hi = min(hi, n_max)
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        if ledger_total_at(params, mid, **kw) <= budget_bytes:
            lo = mid
        else:
            hi = mid
    return lo


# --------------------------------------------------------------------------
# XLA cost harvest (keyed by compile-cache entry)
# --------------------------------------------------------------------------

_harvest_lock = threading.Lock()
_harvest_enabled = False
_dispatch_epoch = 0
_harvests: dict = {}          # key -> record dict
_harvest_failures = 0


def set_harvest_enabled(on: bool) -> None:
    """Master switch (``--capacity-harvest``).  Off (the default) the
    dispatch hook is a single boolean check — zero-cost paths stay
    zero-cost.  On, each NEW compile-cache entry pays one extra XLA
    compile to obtain the analyses (see module docstring)."""
    global _harvest_enabled
    _harvest_enabled = bool(on)


def harvest_enabled() -> bool:
    return _harvest_enabled


def bump_dispatch_epoch() -> None:
    """Called by the resilience supervisor before a retry / CPU-fallback
    re-dispatch: the re-executed unit may compile a different executable
    (other device, fresh buffers), so its harvest must not be served from
    the pre-failure entry."""
    global _dispatch_epoch
    with _harvest_lock:
        _dispatch_epoch += 1


def reset_harvests() -> None:
    """Start-of-run reset (cli main / bench worker), one process == one
    run, same as the span registry."""
    global _dispatch_epoch, _harvest_failures
    with _harvest_lock:
        _harvests.clear()
        _dispatch_epoch = 0
        _harvest_failures = 0


def _leaf_spec(leaf) -> str:
    shp = getattr(leaf, "shape", None)
    dt = getattr(leaf, "dtype", None)
    if shp is not None and dt is not None:
        return f"{dt}{tuple(shp)}"
    return repr(leaf)


def _analyze_compiled(compiled) -> dict:
    """Flatten Compiled.cost_analysis()/memory_analysis() into the
    harvest record schema (missing analyses -> zeros, never a crash)."""
    rec = {"flops": 0.0, "transcendentals": 0.0, "bytes_accessed": 0.0,
           "argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
           "alias_bytes": 0, "generated_code_bytes": 0}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        rec["flops"] = float(ca.get("flops", 0.0))
        rec["transcendentals"] = float(ca.get("transcendentals", 0.0))
        rec["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:  # pragma: no cover - backend-dependent
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            rec["argument_bytes"] = int(ma.argument_size_in_bytes)
            rec["output_bytes"] = int(ma.output_size_in_bytes)
            rec["temp_bytes"] = int(ma.temp_size_in_bytes)
            rec["alias_bytes"] = int(ma.alias_size_in_bytes)
            rec["generated_code_bytes"] = int(
                ma.generated_code_size_in_bytes)
    except Exception:  # pragma: no cover - backend-dependent
        pass
    return rec


def harvest_dispatch(site: str, jitted, args: tuple) -> None:
    """Harvest one engine dispatch (call BEFORE the real jit call — the
    engine donates its state buffers, and ``lower`` only reads avals).

    ``site`` labels the call site (``engine/run_rounds``, ...); the
    harvest key is (site, dispatch epoch, every arg's abstract spec) —
    exactly the information that selects a compile-cache entry, so warm
    calls with the same signature reuse the stored record and pay one
    dict lookup.  Any failure is counted and swallowed: the harvest must
    never kill a run."""
    global _harvest_failures
    if not _harvest_enabled:
        return
    import jax
    key = (site, _dispatch_epoch) + tuple(
        _leaf_spec(leaf) for leaf in jax.tree_util.tree_leaves(args))
    with _harvest_lock:
        rec = _harvests.get(key)
        if rec is not None:
            rec["reused"] += 1
            get_registry().add("capacity/harvest_reused", 1)
            return
    t0 = time.perf_counter()
    try:
        compiled = jitted.lower(*args).compile()
        rec = _analyze_compiled(compiled)
    except Exception as e:  # pragma: no cover - must never kill a run
        with _harvest_lock:
            _harvest_failures += 1
        get_registry().add("capacity/harvest_failures", 1)
        import logging
        logging.getLogger(__name__).warning(
            "WARNING: capacity cost harvest failed for %s (%s); "
            "continuing unharvested", site, e)
        return
    rec.update({"site": site, "reused": 0,
                "harvest_compile_s": round(time.perf_counter() - t0, 3)})
    with _harvest_lock:
        _harvests[key] = rec
    reg = get_registry()
    reg.add("capacity/harvests", 1)
    reg.record("capacity/harvest_compile", rec["harvest_compile_s"])


def harvest_summary() -> dict:
    """Aggregate view for the run report / BENCH lines: totals across
    the distinct harvested executables, peaks for the memory-shaped
    numbers (temp/argument/output are per-executable footprints — their
    max is the planning-relevant figure), and the per-site records."""
    with _harvest_lock:
        recs = [dict(r) for r in _harvests.values()]
        failures = _harvest_failures
    out = {
        "enabled": _harvest_enabled,
        "harvests": len(recs),
        "reused": int(sum(r["reused"] for r in recs)),
        "failures": int(failures),
        "flops": float(sum(r["flops"] for r in recs)),
        "transcendentals": float(sum(r["transcendentals"] for r in recs)),
        "bytes_accessed": float(sum(r["bytes_accessed"] for r in recs)),
        "peak_temp_bytes": int(max((r["temp_bytes"] for r in recs),
                                   default=0)),
        "peak_argument_bytes": int(max((r["argument_bytes"] for r in recs),
                                       default=0)),
        "peak_output_bytes": int(max((r["output_bytes"] for r in recs),
                                     default=0)),
        "generated_code_bytes": int(max(
            (r["generated_code_bytes"] for r in recs), default=0)),
        "sites": {},
    }
    for i, r in enumerate(sorted(recs, key=lambda r: (r["site"],
                                                      -r["temp_bytes"]))):
        out["sites"][f"{r['site']}#{i}"] = r
    return out


def site_peaks(site: str) -> dict:
    """Max temp/argument/output bytes over harvests at exactly ``site``
    (bench.py's per-rung attribution).  Exact match — a prefix would
    silently fold ``engine/run_rounds_lanes`` into ``engine/run_rounds``."""
    with _harvest_lock:
        recs = [r for r in _harvests.values() if r["site"] == site]
    return {
        "temp_bytes": int(max((r["temp_bytes"] for r in recs), default=0)),
        "output_bytes": int(max((r["output_bytes"] for r in recs),
                                default=0)),
        "argument_bytes": int(max((r["argument_bytes"] for r in recs),
                                  default=0)),
        "flops": float(max((r["flops"] for r in recs), default=0.0)),
        "harvests": len(recs),
    }
