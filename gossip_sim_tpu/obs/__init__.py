"""Runtime telemetry: span timers, counters, heartbeats, run reports.

Importing this package stays JAX-free (bench.py's parent process keeps all
JAX touches in subprocesses); the differential-timing helpers live in
:mod:`gossip_sim_tpu.obs.difftime` and import JAX only when called.
"""

from .capacity import (CAPACITY_SCHEMA, capacity_ledger, fit_budget,
                       harvest_summary, parse_size,
                       predict_sim_state_bytes,
                       predict_traffic_state_bytes, set_harvest_enabled)
from .health import (HEALTH_SCHEMA, build_node_health_section, digest_stack,
                     digest_stack_np, stake_decile_ids)
from .heartbeat import Heartbeat
from .report import (PER_CHIP_TARGET, RUN_REPORT_SCHEMA, bench_summary,
                     build_run_report, environment_info, validate_run_report,
                     write_run_report)
from .spans import SpanRegistry, get_registry, span
from .telemetry import (EVENT_SCHEMA, TELEMETRY_SCHEMA, TelemetryHub,
                        emit_event, get_hub, load_event_log,
                        run_key_fingerprint, validate_event,
                        validate_event_log)
from .trace import (TRACE_SCHEMA, OracleTraceCollector, Trace, TraceWriter,
                    load_trace, validate_trace_dir, validate_trace_manifest)

__all__ = [
    "Heartbeat", "SpanRegistry", "get_registry", "span",
    "EVENT_SCHEMA", "TELEMETRY_SCHEMA", "TelemetryHub", "emit_event",
    "get_hub", "load_event_log", "run_key_fingerprint", "validate_event",
    "validate_event_log",
    "PER_CHIP_TARGET", "RUN_REPORT_SCHEMA", "bench_summary",
    "build_run_report", "environment_info", "validate_run_report",
    "write_run_report",
    "TRACE_SCHEMA", "OracleTraceCollector", "Trace", "TraceWriter",
    "load_trace", "validate_trace_dir", "validate_trace_manifest",
    "CAPACITY_SCHEMA", "capacity_ledger", "fit_budget", "harvest_summary",
    "parse_size", "predict_sim_state_bytes", "predict_traffic_state_bytes",
    "set_harvest_enabled",
    "HEALTH_SCHEMA", "build_node_health_section", "digest_stack",
    "digest_stack_np", "stake_decile_ids",
]
