"""Runtime telemetry: span timers, counters, heartbeats, run reports.

Importing this package stays JAX-free (bench.py's parent process keeps all
JAX touches in subprocesses); the differential-timing helpers live in
:mod:`gossip_sim_tpu.obs.difftime` and import JAX only when called.
"""

from .heartbeat import Heartbeat
from .report import (PER_CHIP_TARGET, RUN_REPORT_SCHEMA, bench_summary,
                     build_run_report, environment_info, validate_run_report,
                     write_run_report)
from .spans import SpanRegistry, get_registry, span
from .trace import (TRACE_SCHEMA, OracleTraceCollector, Trace, TraceWriter,
                    load_trace, validate_trace_dir, validate_trace_manifest)

__all__ = [
    "Heartbeat", "SpanRegistry", "get_registry", "span",
    "PER_CHIP_TARGET", "RUN_REPORT_SCHEMA", "bench_summary",
    "build_run_report", "environment_info", "validate_run_report",
    "write_run_report",
    "TRACE_SCHEMA", "OracleTraceCollector", "Trace", "TraceWriter",
    "load_trace", "validate_trace_dir", "validate_trace_manifest",
]
