"""Differential scan timing: split JIT compile cost from steady-state
per-round compute.

A single wall-clock of a jitted ``k``-round scan conflates three costs:
trace+compile, dispatch overhead, and ``k`` rounds of device compute.  The
differential trick (productized out of tools/round_time.py): time a
``k_small``-round call and a ``k_large``-round call (each separately
compiled, each timed post-compile, best-of-``reps``), then

    per_round = (t_large - t_small) / (k_large - k_small)

cancels the fixed dispatch cost exactly and never trusts a first-call
wall.  ``time_stage`` applies the same idea to an arbitrary stage function
(productized out of tools/profile_v2.py): the stage is wrapped in an
iteration-perturbed scan whose carry defeats CSE, so the compiler cannot
hoist the stage out of the loop.

JAX is imported inside the functions: importing :mod:`gossip_sim_tpu.obs`
must never initialize an accelerator backend (bench.py's parent process
keeps every JAX touch in subprocesses).
"""

from __future__ import annotations

import time


def best_of(fn, reps: int = 3) -> float:
    """Minimum wall time of ``fn()`` over ``reps`` calls (noise floor)."""
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def differential_time(run_k, k_small: int = 1, k_large: int = 21,
                      reps: int = 3) -> tuple:
    """Differential per-iteration time of a compiled scan.

    ``run_k(k)`` must execute a ``k``-iteration jitted scan and block until
    the result is ready (each distinct ``k`` compiles its own program).
    Returns ``(per_iter_s, t_small_s)`` where ``t_small_s`` is the
    post-compile best-of wall of the ``k_small`` call — the fixed
    dispatch+single-iteration cost callers print alongside the slope."""
    if k_large <= k_small:
        raise ValueError("k_large must exceed k_small")
    run_k(k_small)                                # compile k_small program
    t_small = best_of(lambda: run_k(k_small), reps)
    run_k(k_large)                                # compile k_large program
    t_large = best_of(lambda: run_k(k_large), reps)
    return (t_large - t_small) / (k_large - k_small), t_small


def make_round_scanner(params, tables, origins, state):
    """``run_k(k)`` running ``k`` full gossip rounds from ``state``.

    The returned callable jit-compiles one scan program per distinct ``k``
    and returns an int reduced from the final state (forcing the device
    computation, defeating dead-code elimination) — exactly the harness
    tools/round_time.py used to hand-roll.  Feed it to
    :func:`differential_time`."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..engine.core import round_step

    @partial(jax.jit, static_argnums=(1,))
    def _run_k(st, k):
        def step(s, it):
            s2, _ = round_step(params, tables, origins, s, it)
            return s2, None
        s, _ = lax.scan(step, st, jnp.arange(k))
        return s.rc_upserts[0, 0] + s.active[0, 0, 0]

    def run_k(k):
        return int(_run_k(state, k))

    return run_k


def time_stage(make_fn, args, reps: int = 10, timing_reps: int = 2) -> float:
    """Differential per-call time of one engine stage (seconds).

    ``make_fn(*args, i)`` builds the stage computation; the extra trailing
    iteration argument must perturb at least one input (``x + i * 0`` is
    enough) so the scan carry feeds the stage and the compiler cannot hoist
    it.  Each scan step reads one data-dependent element of the stage's
    output into the carry, forcing full evaluation per iteration — the
    harness tools/profile_v2.py used to copy-paste per stage."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax import lax

    @partial(jax.jit, static_argnums=(1,))
    def run(args, k):
        def body(c, i):
            out = jnp.ravel(make_fn(*args, i + c))
            pos = ((i * 1297 + c) % out.shape[0]).astype(jnp.int32)
            return lax.dynamic_index_in_dim(
                out, pos, keepdims=False).astype(jnp.int32), None
        c, _ = lax.scan(body, jnp.int32(0), jnp.arange(k))
        return c

    per_call, _ = differential_time(lambda k: int(run(args, k)),
                                    k_small=1, k_large=reps + 1,
                                    reps=timing_reps)
    return per_call
