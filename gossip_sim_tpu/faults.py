"""Network-impairment and fault-injection primitives, shared by both backends.

The reference simulator models exactly one fault: a one-shot random node
failure (gossip.rs:756-771).  This module adds the degraded-network regimes
real gossip runs in — per-message packet loss, continuous fail/recover churn,
and transient stake bipartitions — with one hard requirement: the TPU engine
(engine/core.py) and the CPU oracle (oracle/cluster.py) must make
*bit-identical* impairment decisions under a shared seed, so oracle-vs-engine
parity remains testable under faults (tests/test_faults.py).

That rules out shared stateful RNG streams (the two backends consume
randomness in different orders).  Instead every decision is a *stateless
counter hash*:

    drop(edge)   = fmix32(base_e(seed, it) ^ src*C1 ^ dst*C2)  < p_loss  * 2^32
    fail(node)   = fmix32(base_c(seed, it) ^ node*C1)          < p_fail  * 2^32
    recover(node)= same hash                                   < p_recov * 2^32

``fmix32`` is the murmur3-style 32-bit finalizer; all arithmetic is mod 2^32,
expressible identically in pure-Python ints (oracle) and uint32 lanes
(engine, VPU-elementwise — effectively free at these shapes).  The churn hash
is evaluated once per (iteration, node) and interpreted against the node's
current state, so fail and recover never race.

The partition fault is deterministic given the cluster: a greedy
stake-balanced bipartition (largest stake first onto the lighter side),
active while ``partition_at <= it < heal_at``.  Cross-partition edges are
suppressed (the slot is consumed, nothing is delivered — the same semantics
as pushes to failed nodes, gossip.rs:538-541).

Precedence per push slot: failed target > partition suppression > packet
loss > delivery.  Dropped and suppressed messages consume the fanout slot
and are counted, but contribute nothing to delivery, ingress, consume
ranking, or RMR's m.
"""

from __future__ import annotations

import numpy as np

_M32 = 0xFFFFFFFF
_GOLD = 0x9E3779B1          # 2^32 / phi, round-mixing multiplier
_C1 = 0x85EBCA6B            # murmur3 fmix constants reused as lane salts
_C2 = 0xC2B2AE35
SALT_EDGE = 0x7F4A7C15      # domain separation: packet-loss stream
SALT_CHURN = 0x2545F491     # domain separation: churn stream


def fmix32(x: int) -> int:
    """Murmur3 32-bit finalizer on Python ints (the oracle's scalar path)."""
    x &= _M32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & _M32
    x ^= x >> 15
    x = (x * 0x846CA68B) & _M32
    x ^= x >> 16
    return x


def fmix32_arr(x, xp=np):
    """``fmix32`` on uint32 arrays (numpy or jax.numpy) — multiplication
    wraps mod 2^32 in both, so results match the scalar path bit-for-bit."""
    x = x ^ (x >> 16)
    x = x * xp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * xp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def round_basis(seed: int, it: int, salt: int) -> int:
    """Per-(seed, iteration, domain) hash basis; scalar path."""
    return fmix32((seed & _M32) ^ fmix32((it * _GOLD + salt) & _M32))


def round_basis_arr(seed, it, salt: int, xp=np):
    """``round_basis`` with (possibly traced) uint32 seed/iteration scalars.

    The engine passes both the iteration counter and — since the dynamic-
    knob split (engine/params.py EngineKnobs) — the impairment seed as
    traced scalars, so a seed sweep reuses the compiled round."""
    itu = it.astype(xp.uint32) if hasattr(it, "astype") else xp.uint32(it & _M32)
    h = fmix32_arr(itu * xp.uint32(_GOLD) + xp.uint32(salt), xp)
    seedu = (seed.astype(xp.uint32) if hasattr(seed, "astype")
             else xp.uint32(seed & _M32))
    return fmix32_arr(seedu ^ h, xp)


def edge_u32(basis: int, src: int, dst: int) -> int:
    """Per-edge hash in [0, 2^32); scalar path (oracle)."""
    return fmix32(basis ^ ((src * _C1) & _M32) ^ ((dst * _C2) & _M32))


def edge_u32_arr(basis, src, dst, xp=np):
    """Vectorized ``edge_u32``: basis scalar/array, src/dst uint32 arrays."""
    return fmix32_arr(basis ^ (src * xp.uint32(_C1)) ^ (dst * xp.uint32(_C2)),
                      xp)


def node_u32(basis: int, node: int) -> int:
    """Per-node churn hash in [0, 2^32); scalar path (oracle)."""
    return fmix32(basis ^ ((node * _C1) & _M32))


def node_u32_arr(basis, node, xp=np):
    return fmix32_arr(basis ^ (node * xp.uint32(_C1)), xp)


def rate_threshold(rate: float) -> int:
    """Bernoulli(rate) as an integer threshold: event iff u32 < threshold.

    Exact at the endpoints: rate <= 0 never fires, rate >= 1 always fires
    (threshold 2^32 exceeds every u32, so compare in 64-bit)."""
    if rate <= 0.0:
        return 0
    if rate >= 1.0:
        return 1 << 32
    return int(rate * (1 << 32))


def rate_threshold_arr(rate, xp=np):
    """``rate_threshold`` on a (possibly traced) float scalar -> u64.

    The f64 product truncates toward zero under ``astype``, exactly like
    the scalar path's ``int()`` (rates are nonnegative), so a traced rate
    knob makes bit-identical Bernoulli decisions to the oracle's host
    arithmetic.  Both endpoint exactness guarantees carry over: the
    interior product never reaches 2^32, and the >= 1 branch returns the
    64-bit threshold every u32 hash is below."""
    r = rate.astype(xp.float64) if hasattr(rate, "astype") else xp.float64(rate)
    t = (r * xp.float64(1 << 32)).astype(xp.uint64)
    t = xp.where(r >= 1.0, xp.uint64(1 << 32), t)
    return xp.where(r <= 0.0, xp.uint64(0), t)


def partition_active(it: int, partition_at: int, heal_at: int) -> bool:
    """Partition window: [partition_at, heal_at); heal_at < 0 = never heals."""
    if partition_at < 0:
        return False
    return it >= partition_at and (heal_at < 0 or it < heal_at)


def stake_bipartition(stakes) -> np.ndarray:
    """Deterministic stake-balanced bipartition -> bool side per node.

    Greedy: walk nodes by (stake desc, index asc), assign each to the
    currently lighter side.  Both backends derive the identical split from
    the index-ordered stake vector alone, so no side table needs to be
    communicated."""
    stakes = np.asarray(stakes, dtype=np.int64)
    n = stakes.shape[0]
    # plain-int loop (no per-element numpy scalars): make_cluster_tables
    # builds the split unconditionally, so it must stay cheap at the 32k
    # node cap even on unimpaired runs
    order = np.lexsort((np.arange(n), -stakes)).tolist()
    vals = stakes.tolist()
    side = [False] * n
    tot0 = tot1 = 0
    for i in order:
        if tot1 < tot0:
            side[i] = True
            tot1 += vals[i]
        else:
            tot0 += vals[i]
    return np.asarray(side, dtype=bool)


class FaultInjector:
    """Oracle-side impairment driver (the engine inlines the same hashes in
    engine/core.py round_step).

    Works on a ``NodeIndex`` so the hash inputs are the same dense node ids
    the engine uses; pubkeys are translated at the call boundary.
    """

    def __init__(self, index, seed: int = 0, packet_loss_rate: float = 0.0,
                 churn_fail_rate: float = 0.0,
                 churn_recover_rate: float = 0.0,
                 partition_at: int = -1, heal_at: int = -1):
        self.index = index
        self.seed = int(seed)
        self.loss_thr = rate_threshold(packet_loss_rate)
        self.fail_thr = rate_threshold(churn_fail_rate)
        self.recover_thr = rate_threshold(churn_recover_rate)
        self.partition_at = int(partition_at)
        self.heal_at = int(heal_at)
        self.side = (stake_bipartition(index.stakes.astype(np.int64))
                     if partition_at >= 0 else None)
        # per-round state, set by begin_round()
        self._edge_basis = 0
        self._part_on = False
        self.delivered = 0
        self.dropped = 0
        self.suppressed = 0

    @property
    def has_churn(self) -> bool:
        return self.fail_thr > 0 or self.recover_thr > 0

    def begin_round(self, it: int) -> None:
        self._edge_basis = round_basis(self.seed, it, SALT_EDGE)
        self._part_on = partition_active(it, self.partition_at, self.heal_at)
        self.delivered = 0
        self.dropped = 0
        self.suppressed = 0

    def classify_edge(self, src_pk, dst_pk) -> str:
        """'delivered' | 'suppressed' (partition) | 'dropped' (loss) for one
        push to a live target; counts the outcome."""
        si = self.index.index_of(src_pk)
        di = self.index.index_of(dst_pk)
        if self._part_on and self.side[si] != self.side[di]:
            self.suppressed += 1
            return "suppressed"
        if self.loss_thr and edge_u32(self._edge_basis, si, di) < self.loss_thr:
            self.dropped += 1
            return "dropped"
        self.delivered += 1
        return "delivered"

    def churn_step(self, it: int, node_map, failed_nodes: set) -> tuple:
        """Flip node failure states for iteration ``it``.

        Alive nodes fail with p_fail, failed nodes recover with p_recover —
        one hash per node, interpreted against its current state (mirrors the
        engine's ``jnp.where(failed, ~recover, fail)``).  Updates
        ``node.failed`` and the ``failed_nodes`` set in place; returns
        (newly_failed, newly_recovered) pubkey lists."""
        basis = round_basis(self.seed, it, SALT_CHURN)
        newly_failed, newly_recovered = [], []
        for i, pk in enumerate(self.index.pubkeys):
            node = node_map[pk]
            u = node_u32(basis, i)
            if node.failed:
                if u < self.recover_thr:
                    node.failed = False
                    failed_nodes.discard(pk)
                    newly_recovered.append(pk)
            elif u < self.fail_thr:
                node.failed = True
                failed_nodes.add(pk)
                newly_failed.append(pk)
        return newly_failed, newly_recovered
