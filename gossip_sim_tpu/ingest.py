"""Data ingestion: YAML account files, Solana JSON-RPC, synthetic clusters.

Reference: gossip.rs:883-1005 (cluster factories), gossip_main.rs:304-328
(YAML read), write_accounts_main.rs:118-125 (YAML write).
"""

from __future__ import annotations

import json
import logging
import urllib.request

import yaml

from .constants import LAMPORTS_PER_SOL
from .identity import Pubkey, pubkey_new_unique

log = logging.getLogger(__name__)


def load_accounts_yaml(path: str) -> dict:
    """Read a {pubkey_str: stake} YAML account file (gossip_main.rs:304-318)."""
    with open(path) as f:
        accounts = yaml.safe_load(f) or {}
    log.info("%s accounts read in", len(accounts))
    return {Pubkey.from_string(k): int(v) for k, v in accounts.items()}


def write_accounts_yaml(path: str, accounts: dict) -> None:
    """Write {pubkey: stake} as YAML (write_accounts_main.rs:118-125)."""
    out = {(pk.to_string() if isinstance(pk, Pubkey) else str(pk)): int(stake)
           for pk, stake in accounts.items()}
    with open(path, "w") as f:
        yaml.safe_dump(out, f, default_flow_style=False)


def fetch_vote_accounts_rpc(json_rpc_url: str, timeout: float = 30.0) -> dict:
    """Pull vote accounts via ``getVoteAccounts`` and aggregate activated
    stake per node pubkey over current + delinquent accounts
    (gossip.rs:936-967; keeps unstaked delinquents, finalized commitment)."""
    payload = {
        "jsonrpc": "2.0",
        "id": 1,
        "method": "getVoteAccounts",
        "params": [{"commitment": "finalized", "keepUnstakedDelinquents": True}],
    }
    req = urllib.request.Request(
        json_rpc_url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        result = json.load(resp)["result"]
    log.info("num of vote accounts: %s",
             len(result["current"]) + len(result["delinquent"]))
    stakes: dict = {}
    for info in list(result["current"]) + list(result["delinquent"]):
        key = info["nodePubkey"]
        stakes[key] = stakes.get(key, 0) + int(info["activatedStake"])
    return {Pubkey.from_string(k): v for k, v in stakes.items()}


def filter_accounts(accounts: dict, filter_zero_staked: bool) -> dict:
    """Optionally drop zero-staked nodes (gossip.rs:892-894)."""
    if not filter_zero_staked:
        return dict(accounts)
    return {pk: s for pk, s in accounts.items() if s != 0}


def synthetic_accounts(num_nodes: int, rng, max_stake_sol: int = 1 << 20) -> dict:
    """Deterministic synthetic cluster: counter pubkeys + uniform stakes in
    [1, max_stake_sol * LAMPORTS_PER_SOL) — the reference test-fixture recipe
    (gossip.rs:1044-1050)."""
    max_stake = max_stake_sol * LAMPORTS_PER_SOL
    return {pubkey_new_unique(): rng.gen_range_u64(1, max_stake)
            for _ in range(num_nodes)}


def log_cluster_summary(accounts: dict) -> None:
    """(gossip.rs:914-923)"""
    staked = sum(1 for s in accounts.values() if s != 0)
    log.info("num of staked nodes in cluster: %s", staked)
    log.info("num of cluster nodes: %s", len(accounts))
    log.info("cluster stake: %s", sum(accounts.values()))
