"""Concurrent CRDS traffic: M-value message streams, shared by both backends.

Every number the simulator produced before this module describes ONE origin
value diffusing through an otherwise idle network.  Production Solana push
gossip carries thousands of concurrent CRDS values contending for the same
active sets, prune state, and per-node ingress budgets (ROADMAP item 4).
This module defines the traffic model both backends implement bit-exactly:

* **One shared network.**  All in-flight values push through ONE [N, S]
  active set with ONE rotation schedule.  Prune bits and received-cache
  scoring stay keyed per value (Solana prunes per *origin*; counter-hashed
  injection origins are almost always distinct, so value == origin key is
  the documented simplification), but they live on the *shared* slots: a
  rotation evicts the pruned bits of every value at once.
* **Deterministic stake-weighted injection.**  Round ``it`` injects
  ``traffic_rate`` new values at origins drawn from the stake-class CDF
  (the pull subsystem's top-entry ``(bucket+1)^2`` weights) with counter-
  hash uniforms of ``(impair_seed, it, j)`` — the faults.py discipline, so
  the schedule replays identically on engine, oracle, resume, and sweeps.
  Values occupy one of ``traffic_values`` capacity slots; when no slot is
  free the injection is *dropped* (counted, never silent).
* **Hop-per-round propagation.**  Unlike the single-value engine's
  full-BFS-per-round model, a traffic value advances one hop per round:
  every holder pushes it to its first ``push_fanout`` valid shared-set
  slots each round.  This is the standard discrete-time push-gossip model
  and is what makes per-node queue caps meaningful: contention happens
  *within* a round, across values.
* **Queue caps create real contention.**  ``node_egress_cap`` bounds the
  messages a node may put on the wire per round across ALL values (excess
  candidates are **deferred** — the slot retries next round, a queue);
  ``node_ingress_cap`` bounds the messages a node accepts per round
  (excess arrivals are **dropped**).  Per-slot precedence extends the
  faults.py contract:

      egress-deferred > failed target > partition suppressed >
      packet loss > ingress-dropped > accepted

  with egress ranked in (value, fanout-slot) order per sender and ingress
  in (value, source, fanout-slot) order per receiver — both deterministic
  and identical in the two backends.
* **Per-value lifecycle.**  A value retires when every node holds it
  (converged) or when it makes no delivery progress for
  ``traffic_stall_rounds`` consecutive rounds (stranded/partial); its slot
  recycles for later injections.  Retirement emits a per-value record
  (origin, birth, latency in rounds, coverage, message count, RMR) that
  flows into ``stats/traffic.py``, the ``sim_traffic`` Influx series and
  the run report.

Determinism contract (the faults.py philosophy): every stochastic choice
is a *stateless counter hash* — injection origins, packet loss (decorrelated
per value via ``value_basis``), the shared active-set initialization, and
the shared rotation schedule (event uniform + candidate draws).  The
engine's vectorized draws and the oracle's loops share the `*_arr` helpers
below (identical IEEE f32 arithmetic), so ``TrafficOracle`` is bit-exact
against the sort-routed engine under loss + churn with rotation ON —
stronger than the push path's parity tests, which must force rotation off.

With ``traffic_values == 1`` and both caps disabled the traffic subsystem
is *off*: the CLI runs the unmodified single-value engine and every output
(stats parity snapshot, Influx wire lines, trace events) is bit-identical
to the pre-traffic simulator — the same gating contract as pull's
``gossip_mode=push``.

Everything here is numpy-only: importing this module never touches JAX.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .adaptive import (SALT_ADAPT_PBLOOM, SALT_ADAPT_PCLASS,
                       SALT_ADAPT_PLOSS, SALT_ADAPT_PMEMBER, switch_update)
from .faults import (_GOLD, edge_u32, edge_u32_arr, fmix32, fmix32_arr,
                     node_u32, node_u32_arr, partition_active, rate_threshold,
                     round_basis, stake_bipartition)
from .pull import PullTables, pull_class_tables, u01_from_u32

# domain-separation salts for the traffic hash streams (faults.py
# convention; SHA-256 round constants, distinct from every existing salt)
SALT_TRAFFIC_OCLASS = 0x52DCE729   # injection origin: stake-class uniform
SALT_TRAFFIC_OMEMBER = 0x1F83D9AB  # injection origin: within-class uniform
SALT_TRAFFIC_LOSS = 0x5BE0CD19     # per-(value, src, dst) packet loss
SALT_TRAFFIC_ROT = 0x428A2F98      # shared rotation: per-node event uniform
SALT_TRAFFIC_RCLASS = 0x71374491   # rotation candidate: class uniform
SALT_TRAFFIC_RMEMBER = 0xB5C0FBCF  # rotation candidate: member uniform
SALT_TRAFFIC_ICLASS = 0xE9B5DBA5   # shared-set init: class uniform
SALT_TRAFFIC_IMEMBER = 0x3956C25B  # shared-set init: member uniform

# per-candidate-slot outcome codes.  0-4 are the flight-recorder TRACE_*
# codes (obs/trace.py) so stats/edges.py explain-stranded reads traffic
# events unchanged; 5-6 are the queue-cap outcomes this subsystem adds
# (trace schema v3).
TRAFFIC_EMPTY = 0            # no candidate in this slot
TRAFFIC_ACCEPTED = 1         # == TRACE_CANDIDATE: arrived and accepted
TRAFFIC_FAILED_TARGET = 2    # == TRACE_FAILED_TARGET
TRAFFIC_SUPPRESSED = 3       # == TRACE_SUPPRESSED (partition)
TRAFFIC_DROPPED = 4          # == TRACE_DROPPED (packet loss)
TRAFFIC_DEFERRED = 5         # sender's node_egress_cap exhausted (queued)
TRAFFIC_QUEUE_DROPPED = 6    # receiver's node_ingress_cap exhausted
TRAFFIC_CODE_NAMES = {
    TRAFFIC_EMPTY: "empty",
    TRAFFIC_ACCEPTED: "accepted",
    TRAFFIC_FAILED_TARGET: "failed_target",
    TRAFFIC_SUPPRESSED: "suppressed",
    TRAFFIC_DROPPED: "dropped",
    TRAFFIC_DEFERRED: "deferred",
    TRAFFIC_QUEUE_DROPPED: "queue_dropped",
}

_M32 = 0xFFFFFFFF


def value_basis(basis: int, vid: int) -> int:
    """Decorrelate a per-round hash basis per value id (scalar path).

    Without this, two values crossing the same edge in the same round
    would share one loss coin — a correlated "link down" model.  Folding
    the (globally unique, monotone) value id in gives every value an
    independent stream while staying stateless and replayable."""
    return fmix32((basis ^ ((vid * _GOLD) & _M32)) & _M32)


def value_basis_arr(basis, vid, xp=np):
    """``value_basis`` on uint32 lanes (vid array -> basis array)."""
    return fmix32_arr(basis ^ (vid.astype(xp.uint32) * xp.uint32(_GOLD)), xp)


def u01_arr(h, xp=np):
    """u32 hash array -> f32 uniforms in [0, 1): ``(h >> 8) * 2^-24``.

    The 24 surviving bits fit the f32 mantissa exactly, so numpy (oracle)
    and jax.numpy (engine) lanes produce identical values (pull.py
    ``u01_from_u32`` is the scalar twin)."""
    return (h >> xp.uint32(8)).astype(xp.float32) * xp.float32(2.0 ** -24)


class TrafficTables(NamedTuple):
    """Stake-class sampling tables for every traffic draw (numpy).

    Wraps the pull subsystem's top-entry class CDF (``(bucket+1)^2``
    weights) — injection origins, the shared active set and rotation
    candidates are all origin-independent draws, exactly the profile the
    pull sampler already factorizes.  The engine mirrors these arrays onto
    the device; both backends run :func:`class_draw_arr` over them."""

    perm: np.ndarray         # [N] i32 node ids sorted by bucket (stable)
    class_start: np.ndarray  # [NB] i32
    class_count: np.ndarray  # [NB] i32
    cdf: np.ndarray          # [NB] f32 inclusive CDF, cdf[-1] == 1.0


def traffic_tables(stakes) -> TrafficTables:
    pt: PullTables = pull_class_tables(stakes)
    return TrafficTables(perm=pt.perm, class_start=pt.class_start,
                         class_count=pt.class_count, cdf=pt.cdf)


def class_draw_arr(tables, u_cls, u_mem, xp=np):
    """Vectorized stake-weighted node draw, shared by both backends.

    ``u_cls``/``u_mem``: f32 uniform arrays of any (equal) shape; returns
    the drawn node ids (same shape, i32; may include the drawer itself —
    callers discard self-draws).  All arithmetic is f32/i32-exact between
    numpy and jax.numpy lanes: a class compare against the shared CDF, a
    ``floor(u * count)`` within the class, and a permutation gather."""
    cdf = xp.asarray(tables.cdf)
    start = xp.asarray(tables.class_start)
    count = xp.asarray(tables.class_count)
    perm = xp.asarray(tables.perm)
    nb = tables.cdf.shape[0]
    shape = u_cls.shape
    uc = u_cls.reshape(-1)
    um = u_mem.reshape(-1)
    cls = xp.sum((uc[:, None] >= cdf[None, :-1]).astype(xp.int32), axis=-1)
    oh = (cls[:, None] == xp.arange(nb, dtype=xp.int32)[None, :])
    ohf = oh.astype(xp.float32)
    cstart = xp.einsum("xc,c->x", ohf,
                       start.astype(xp.float32)).astype(xp.int32)
    ccount = xp.einsum("xc,c->x", ohf,
                       count.astype(xp.float32)).astype(xp.int32)
    pos = cstart + xp.floor(um * ccount.astype(xp.float32)).astype(xp.int32)
    pos = xp.minimum(pos, cstart + xp.maximum(ccount - 1, 0))
    return perm[pos].reshape(shape)


def build_shared_active_set(stakes, seed: int, active_set_size: int,
                            init_draws: int) -> np.ndarray:
    """The ONE [N, S] active set every traffic value pushes through.

    Per node: ``init_draws`` stake-weighted candidate draws (counter
    hashes of ``(seed, node, draw)`` under the init salts), keeping the
    first S distinct non-self candidates.  Unfilled slots hold N (empty).
    Pure numpy and deterministic, so both backends call this exact
    function — shared-code parity rather than dual implementations."""
    stakes = np.asarray(stakes, dtype=np.int64)
    n = int(stakes.shape[0])
    s = int(active_set_size)
    e = int(init_draws)
    tables = traffic_tables(stakes)
    b_ic = round_basis(seed, 0, SALT_TRAFFIC_ICLASS)
    b_im = round_basis(seed, 0, SALT_TRAFFIC_IMEMBER)
    nodes_u = np.arange(n, dtype=np.uint32)[:, None]
    draws_u = np.arange(e, dtype=np.uint32)[None, :]
    u_cls = u01_arr(edge_u32_arr(np.uint32(b_ic), nodes_u, draws_u))
    u_mem = u01_arr(edge_u32_arr(np.uint32(b_im), nodes_u, draws_u))
    cands = class_draw_arr(tables, u_cls, u_mem)          # [N, E]
    active = np.full((n, s), n, np.int32)
    cnt = np.zeros(n, np.int32)
    self_idx = np.arange(n, dtype=np.int32)
    for d in range(e):
        c = cands[:, d].astype(np.int32)
        dup = np.any(active == c[:, None], axis=-1) | (c == self_idx)
        ins = (~dup) & (cnt < s)
        slot = np.minimum(cnt, s - 1)
        active[np.nonzero(ins)[0], slot[ins]] = c[ins]
        cnt += ins.astype(np.int32)
    return active


class TrafficRound(NamedTuple):
    """One round's traffic outcome (oracle side; the engine's
    ``traffic_round_step`` emits the same quantities as rows)."""

    injected: int            # values injected this round
    inject_dropped: int      # injections lost to a full slot table
    live: int                # live values AFTER injection+retirement
    sends: int               # messages put on the wire (egress-cap survivors)
    deferred: int            # candidates deferred by node_egress_cap
    failed_target: int       # sends into churn-failed targets
    suppressed: int          # partition-suppressed sends
    dropped: int             # loss-dropped sends
    arrived: int             # sends that reached a live receiver
    queue_dropped: int       # arrivals dropped by node_ingress_cap
    accepted: int            # arrivals accepted (delivered + redundant)
    delivered: int           # first deliveries (new (value, node) pairs)
    redundant: int           # accepted copies beyond the first delivery
    prunes_sent: int         # prune messages across values
    retired: int             # values retired this round
    converged: int           # retired with full coverage
    hop_clamped: int         # first deliveries whose true hop exceeded H-1
    qdepth_max: int          # max per-node deferred count this round
    inflow_max: int          # max per-node accepted ingress this round
    records: list            # retirement record dicts (see retire_record)
    node_deferred: np.ndarray      # [N] i64 deferrals per sender
    node_queue_dropped: np.ndarray  # [N] i64 ingress drops per receiver
    # adaptive pull-rescue counters (adaptive.py; all zero outside
    # gossip_mode="adaptive" — trailing defaults keep push-mode rounds
    # constructing exactly as before)
    pull_sent: int = 0           # rescue requests put on the wire
    pull_deferred: int = 0       # requests deferred by node_egress_cap
    pull_failed_target: int = 0  # requests into churn-failed peers
    pull_suppressed: int = 0     # partition-suppressed requests
    pull_dropped: int = 0        # loss-dropped requests
    pull_arrived: int = 0        # requests that reached a live peer
    pull_queue_dropped: int = 0  # arrivals dropped by node_ingress_cap
    pull_served: int = 0         # arrivals accepted into the peer's budget
    pull_responses: int = 0      # value transfers back to requesters
    pull_rescued: int = 0        # first deliveries via pull this round
    pull_active_values: int = 0  # live values in their pull phase
    switched_to_pull: int = 0    # values flipping push -> pull this round
    # node-health observatory planes (obs/health.py): the oracle twins of
    # the engine's TrafficState health accumulators, filled every round by
    # run_round (trailing defaults keep hand-built rounds constructing);
    # the 1k-node parity test diffs their warm-gated sums bit-for-bit
    node_sent: np.ndarray = None        # [N] i64 wire messages per sender
    node_recv: np.ndarray = None        # [N] i64 accepted per receiver
    node_prune_sent: np.ndarray = None  # [N] i64 prunes per pruner
    node_prune_recv: np.ndarray = None  # [N] i64 prunes per prunee
    node_delivered: np.ndarray = None   # [N] i64 first deliveries
    node_lat_sum: np.ndarray = None     # [N] i64 sum of first-delivery
    #                                     latencies (it - birth + 1)
    node_rescued: np.ndarray = None     # [N] i64 pull-rescue deliveries


#: terminal causes a retirement record carries (the starvation
#: root-causing contract: every retired value says WHY it retired)
CAUSE_CONVERGED = "converged"              # full coverage, push alone
CAUSE_RESCUED_BY_PULL = "rescued_by_pull"  # full coverage, pull finished it
CAUSE_STARVED_QUEUE_DROP = "starved_queue_drop"  # stalled with queue drops
CAUSE_STALLED = "stalled"                  # stalled, no queue drop involved


def terminal_cause(full: bool, rescued: int, qdrops: int) -> str:
    """The explicit terminal cause of a retired value.  A converged value
    that needed pull deliveries retires ``rescued_by_pull``; an
    unconverged one whose messages hit an ingress queue drop retires
    ``starved_queue_drop`` (the BENCH_r07 failure mode), else plain
    ``stalled``."""
    if full:
        return CAUSE_RESCUED_BY_PULL if rescued > 0 else CAUSE_CONVERGED
    return CAUSE_STARVED_QUEUE_DROP if qdrops > 0 else CAUSE_STALLED


def retire_record(vid, origin, birth, it, holders, n, m_msgs, full,
                  hops_sum, rescued=0, qdrops=0) -> dict:
    """The per-value retirement record both backends emit (and the stats
    layer, Influx series, and run report consume).  ``latency_rounds``
    counts rounds in flight inclusive of the injection round; RMR follows
    the push path's ``m/(n-1) - 1`` with m = accepted messages + prunes.
    ``rescued``/``qdrops`` root-cause the terminal state: pull-rescue
    deliveries the value received (adaptive.py) and ingress queue drops
    that hit its messages."""
    holders = int(holders)
    return {
        "vid": int(vid),
        "origin": int(origin),
        "birth": int(birth),
        "retired_at": int(it),
        "latency_rounds": int(it) - int(birth) + 1,
        "holders": holders,
        "coverage": holders / float(n),
        "m": int(m_msgs),
        "rmr": (m_msgs / (holders - 1) - 1.0) if holders > 1 else 0.0,
        "converged": bool(full),
        "mean_hop": (hops_sum / holders) if holders > 0 else 0.0,
        "rescued_by_pull": int(rescued),
        "qdrops": int(qdrops),
        "cause": terminal_cause(bool(full), int(rescued), int(qdrops)),
    }


class TrafficOracle:
    """CPU-oracle traffic engine: the identical spec as
    ``engine/traffic.py traffic_round_step``, implemented as plain
    per-value / per-node / per-slot loops — an independent formulation the
    1k-node parity test (tests/test_traffic.py) checks the sort-routed
    engine against bit-for-bit, including rotation (hash-based here, so it
    needs no forced-identical-active-set scaffolding).

    State layout mirrors the engine's ``TrafficState``: ``slots`` holds
    per-value dicts (None = free slot), everything shared lives on the
    instance.  ``run_round`` returns a :class:`TrafficRound`.
    """

    def __init__(self, stakes, *, seed: int = 42, impair_seed: int = 0,
                 traffic_values: int = 8, traffic_rate: int = 1,
                 node_ingress_cap: int = 0, node_egress_cap: int = 0,
                 traffic_stall_rounds: int = 3,
                 push_fanout: int = 6, active_set_size: int = 12,
                 init_draws: int = 64, k_inbound: int = 16,
                 received_cap: int = 50, rc_slots: int = 64,
                 min_num_upserts: int = 20,
                 prune_stake_threshold: float = 0.15,
                 min_ingress_nodes: int = 2,
                 probability_of_rotation: float = 0.013333,
                 rot_tries: int = 8, hist_bins: int = 64,
                 packet_loss_rate: float = 0.0,
                 churn_fail_rate: float = 0.0,
                 churn_recover_rate: float = 0.0,
                 partition_at: int = -1, heal_at: int = -1,
                 gossip_mode: str = "push",
                 adaptive_switch_threshold: float = 0.9,
                 adaptive_switch_hysteresis: float = 0.05,
                 pull_fanout: int = 2, pull_slots: int = 0,
                 pull_bloom_fp_rate: float = 0.1):
        stakes = np.asarray(stakes, dtype=np.int64)
        self.stakes = stakes
        self.n = int(stakes.shape[0])
        self.tables = traffic_tables(stakes)
        self.seed = int(seed)
        self.impair_seed = int(impair_seed)
        self.mv = int(traffic_values)
        self.rate = int(traffic_rate)
        self.ingress_cap = int(node_ingress_cap)
        self.egress_cap = int(node_egress_cap)
        self.stall_rounds = int(traffic_stall_rounds)
        self.fanout = min(int(push_fanout), int(active_set_size))
        self.s = int(active_set_size)
        self.k_inbound = int(k_inbound)
        self.received_cap = int(received_cap)
        self.rc_slots = int(rc_slots)
        self.min_num_upserts = int(min_num_upserts)
        self.prune_stake_threshold = float(prune_stake_threshold)
        self.min_ingress_nodes = int(min_ingress_nodes)
        self.rot_prob = np.float32(probability_of_rotation)
        self.rot_tries = int(rot_tries)
        self.hist_bins = int(hist_bins)
        self.loss_thr = rate_threshold(packet_loss_rate)
        self.fail_thr = rate_threshold(churn_fail_rate)
        self.recover_thr = rate_threshold(churn_recover_rate)
        self.partition_at = int(partition_at)
        self.heal_at = int(heal_at)
        self.side = (stake_bipartition(stakes)
                     if self.partition_at >= 0 else None)
        # adaptive pull-rescue (adaptive.py); inert outside mode adaptive
        self.adaptive = gossip_mode == "adaptive"
        self.adapt_thr = float(adaptive_switch_threshold)
        self.adapt_hyst = float(adaptive_switch_hysteresis)
        self.pull_fanout = int(pull_fanout)
        self.pull_slots = (int(pull_slots) if pull_slots > 0
                           else max(8, self.pull_fanout))
        self.pull_fp_thr = rate_threshold(pull_bloom_fp_rate)

        self.active = build_shared_active_set(stakes, self.seed, self.s,
                                              init_draws)
        self.failed = np.zeros(self.n, bool)
        self.next_vid = 0
        # per-value slots: None = free, else a dict of per-value state
        self.slots = [None] * self.mv

    # -- per-value slot state ---------------------------------------------

    def _fresh_slot(self, vid: int, origin: int, it: int) -> dict:
        holder = np.zeros(self.n, bool)
        holder[origin] = True
        hop = np.full(self.n, -1, np.int32)
        hop[origin] = 0
        return {
            "vid": vid, "origin": origin, "birth": it, "stall": 0,
            "holder": holder, "hop": hop, "m": 0,
            "pruned": np.zeros((self.n, self.s), bool),
            # received cache: per node, {src: [score, stake]} + upserts
            "rc": [dict() for _ in range(self.n)],
            "rc_upserts": np.zeros(self.n, np.int32),
            # adaptive direction state + starvation root-cause counters
            "pull": False,     # value is in its pull-rescue phase
            "rescued": 0,      # nodes delivered via pull rescue
            "qdrop": 0,        # ingress queue drops that hit this value
        }

    # -- the round --------------------------------------------------------

    def churn_step(self, it: int) -> None:
        if self.fail_thr == 0 and self.recover_thr == 0:
            return
        from .faults import SALT_CHURN, node_u32
        basis = round_basis(self.impair_seed, it, SALT_CHURN)
        for i in range(self.n):
            u = node_u32(basis, i)
            if self.failed[i]:
                if u < self.recover_thr:
                    self.failed[i] = False
            elif u < self.fail_thr:
                self.failed[i] = True

    def inject(self, it: int):
        """Round-start injection; returns (injected, dropped)."""
        rate = max(0, min(self.rate, self.mv))
        free = [m for m in range(self.mv) if self.slots[m] is None]
        n_inj = min(rate, len(free))
        from .faults import node_u32
        from .pull import u01_from_u32
        b_oc = round_basis(self.impair_seed, it, SALT_TRAFFIC_OCLASS)
        b_om = round_basis(self.impair_seed, it, SALT_TRAFFIC_OMEMBER)
        for j in range(n_inj):
            u_cls = u01_from_u32(node_u32(b_oc, j))
            u_mem = u01_from_u32(node_u32(b_om, j))
            origin = int(class_draw_arr(self.tables,
                                        np.asarray([u_cls], np.float32),
                                        np.asarray([u_mem], np.float32))[0])
            self.slots[free[j]] = self._fresh_slot(self.next_vid + j,
                                                   origin, it)
        self.next_vid += n_inj
        return n_inj, rate - n_inj

    def run_round(self, it: int) -> TrafficRound:
        n, s, f = self.n, self.s, self.fanout
        self.churn_step(it)
        injected, inject_dropped = self.inject(it)
        live_slots = [m for m in range(self.mv) if self.slots[m] is not None]

        part_on = (self.side is not None
                   and partition_active(it, self.partition_at, self.heal_at))
        b_loss = round_basis(self.impair_seed, it, SALT_TRAFFIC_LOSS)

        # ---- candidate pushes, egress cap, network classification -------
        # (value asc, sender, fanout-slot asc) walk == the engine's
        # m-major egress ranking per sender
        egress_used = np.zeros(n, np.int64)
        node_deferred = np.zeros(n, np.int64)
        node_qdrop = np.zeros(n, np.int64)
        # node-health planes (engine TrafficState health twins)
        node_sent = np.zeros(n, np.int64)
        node_recv = np.zeros(n, np.int64)
        node_prune_sent = np.zeros(n, np.int64)
        node_prune_recv = np.zeros(n, np.int64)
        node_delivered = np.zeros(n, np.int64)
        node_lat_sum = np.zeros(n, np.int64)
        node_rescued = np.zeros(n, np.int64)
        sends = deferred = failed_target = suppressed = dropped = 0
        pull_active_values = sum(
            1 for m in live_slots if self.slots[m]["pull"])
        arrivals = []   # (value-slot m, src, fanout-slot, dst) in order
        for m in live_slots:
            v = self.slots[m]
            if v["pull"]:
                # adaptive direction flip: a pull-phase value generates NO
                # push candidates — its bandwidth share moves to the
                # rescue requests of the nodes still missing it
                continue
            vb = value_basis(b_loss, v["vid"])
            for src in range(n):
                if not v["holder"][src] or self.failed[src]:
                    continue
                used_f = 0
                for slot in range(s):
                    peer = int(self.active[src, slot])
                    if peer >= n or v["pruned"][src, slot] \
                            or peer == v["origin"]:
                        continue
                    if used_f >= f:
                        break
                    used_f += 1
                    # a candidate occupies a fanout slot; egress cap next
                    if 0 < self.egress_cap <= egress_used[src]:
                        deferred += 1
                        node_deferred[src] += 1
                        continue
                    egress_used[src] += 1
                    sends += 1
                    node_sent[src] += 1
                    if self.failed[peer]:
                        failed_target += 1
                        continue
                    if part_on and self.side[src] != self.side[peer]:
                        suppressed += 1
                        continue
                    if (self.loss_thr
                            and edge_u32(vb, src, peer) < self.loss_thr):
                        dropped += 1
                        continue
                    arrivals.append((m, src, peer))
        arrived = len(arrivals)

        # ---- ingress cap in (value, src, slot) arrival order ------------
        ingress_used = np.zeros(n, np.int64)
        accepted = []   # (m, src, dst)
        queue_dropped = 0
        for (m, src, dst) in arrivals:
            if 0 < self.ingress_cap <= ingress_used[dst]:
                queue_dropped += 1
                node_qdrop[dst] += 1
                self.slots[m]["qdrop"] += 1
                continue
            ingress_used[dst] += 1
            node_recv[dst] += 1
            accepted.append((m, src, dst))

        # ---- adaptive pull-rescue phase (adaptive.py) -------------------
        # Per pull-phase value, every live node still missing it sends
        # pull_fanout stake-weighted requests.  Requests continue the SAME
        # egress/ingress budgets the push phase just consumed (value-major
        # order after all push messages), so rescues pay for bandwidth
        # honestly; a holder answers an accepted request unless the
        # requester's per-value bloom digest false-positives.  Responses
        # ride the reverse path of an accepted request (documented
        # simplification: they do not re-enter the queue ranking) and the
        # requester keeps the minimum (hop, clamp, peer) response.
        pull_sent = pull_deferred = pull_failed_target = 0
        pull_suppressed = pull_dropped = pull_arrived = 0
        pull_qdropped = pull_served = pull_responses = 0
        pull_rescues = {}   # (m, dst) -> (clamped hop, clamp bit, peer)
        if self.adaptive and pull_active_values:
            b_pc = round_basis(self.impair_seed, it, SALT_ADAPT_PCLASS)
            b_pm = round_basis(self.impair_seed, it, SALT_ADAPT_PMEMBER)
            b_pl = round_basis(self.impair_seed, it, SALT_ADAPT_PLOSS)
            b_pb = round_basis(self.impair_seed, it, SALT_ADAPT_PBLOOM)
            preq = []   # (m, requester, slot, peer, fp) in arrival order
            for m in live_slots:
                v = self.slots[m]
                if not v["pull"]:
                    continue
                vid = v["vid"]
                vb_c = value_basis(b_pc, vid)
                vb_m = value_basis(b_pm, vid)
                vb_l = value_basis(b_pl, vid)
                vb_b = value_basis(b_pb, vid)
                for d in range(n):
                    if self.failed[d] or v["holder"][d]:
                        continue
                    fp_d = bool(self.pull_fp_thr
                                and node_u32(vb_b, d) < self.pull_fp_thr)
                    # NB: the slot index must NOT be named ``s`` — that
                    # would clobber the active-set size the prune-apply
                    # and rotation loops below still read this round
                    for ps in range(min(self.pull_fanout, self.pull_slots)):
                        peer = int(class_draw_arr(
                            self.tables,
                            np.asarray([u01_from_u32(edge_u32(vb_c, d, ps))],
                                       np.float32),
                            np.asarray([u01_from_u32(edge_u32(vb_m, d, ps))],
                                       np.float32))[0])
                        if peer == d:
                            continue   # self-draw: slot discarded
                        if 0 < self.egress_cap <= egress_used[d]:
                            pull_deferred += 1
                            node_deferred[d] += 1
                            continue
                        egress_used[d] += 1
                        pull_sent += 1
                        node_sent[d] += 1
                        if self.failed[peer]:
                            pull_failed_target += 1
                            continue
                        if part_on and self.side[d] != self.side[peer]:
                            pull_suppressed += 1
                            continue
                        if (self.loss_thr
                                and edge_u32(vb_l, d, peer) < self.loss_thr):
                            pull_dropped += 1
                            continue
                        pull_arrived += 1
                        preq.append((m, d, peer, fp_d))
            for (m, d, peer, fp_d) in preq:
                if 0 < self.ingress_cap <= ingress_used[peer]:
                    pull_qdropped += 1
                    node_qdrop[peer] += 1
                    self.slots[m]["qdrop"] += 1
                    continue
                ingress_used[peer] += 1
                pull_served += 1
                node_recv[peer] += 1
                v = self.slots[m]
                v["m"] += 1
                if v["holder"][peer] and not fp_d:
                    pull_responses += 1
                    # a response is peer egress + requester ingress (the
                    # engine's resp_peer / resp_in accounting)
                    node_sent[peer] += 1
                    node_recv[d] += 1
                    v["m"] += 1
                    th = int(v["hop"][peer]) + 1
                    key = (min(th, self.hist_bins - 1),
                           1 if th > self.hist_bins - 1 else 0, peer)
                    cur = pull_rescues.get((m, d))
                    if cur is None or key < cur:
                        pull_rescues[(m, d)] = key

        # ---- per-value inbound ranking, delivery, rc merge, prunes ------
        h_clamp = self.hist_bins - 1
        n_accepted = len(accepted)
        prunes_sent = hop_clamped = 0
        progress = {m: 0 for m in live_slots}
        inbound = {}   # (m, dst) -> [(clamped hop, src, true hop)]
        for (m, src, dst) in accepted:
            v = self.slots[m]
            th = int(v["hop"][src]) + 1
            inbound.setdefault((m, dst), []).append(
                (min(th, h_clamp), src, th))
            v["m"] += 1

        new_hops = {}
        for (m, dst), lst in inbound.items():
            v = self.slots[m]
            lst.sort(key=lambda e: (e[0], e[1]))
            lst[:] = lst[:self.k_inbound]    # the engine's k_inbound width
            if not v["holder"][dst]:
                ch, _, th = lst[0]
                new_hops[(m, dst)] = ch
                progress[m] += 1
                if th > h_clamp:
                    hop_clamped += 1
            # received-cache merge (engine verb-2 tail semantics)
            rc = v["rc"][dst]
            length = len(rc)
            for r, (_, src, _) in enumerate(lst):
                if src in rc:
                    if r < 2:
                        rc[src][0] += 1
                elif (r < 2) or (length < self.received_cap):
                    rc[src] = [1 if r < 2 else 0, int(self.stakes[src])]
                    length += 1
            if len(rc) > self.rc_slots:
                # physical-slot eviction: keep the rc_slots smallest ids
                for src in sorted(rc)[self.rc_slots:]:
                    del rc[src]
            v["rc_upserts"][dst] += 1
        for (m, dst), hp in new_hops.items():
            v = self.slots[m]
            v["holder"][dst] = True
            v["hop"][dst] = hp
            node_delivered[dst] += 1
            node_lat_sum[dst] += it - v["birth"] + 1
        # first deliveries = new (value, node) pairs; every further
        # accepted copy (same-round duplicates included) is redundant
        delivered = len(new_hops)
        redundant = n_accepted - delivered
        # pull-rescue deliveries apply after push deliveries (one
        # request/response exchange per round, no intra-round cascade)
        pull_rescued_cnt = 0
        for (m, dst), (ch, clamp, _peer) in pull_rescues.items():
            v = self.slots[m]
            if v["holder"][dst]:
                continue
            v["holder"][dst] = True
            v["hop"][dst] = ch
            v["rescued"] += 1
            pull_rescued_cnt += 1
            node_delivered[dst] += 1
            node_lat_sum[dst] += it - v["birth"] + 1
            node_rescued[dst] += 1
            progress[m] += 1
            if clamp:
                hop_clamped += 1

        # ---- prune decide + apply (per value, engine verbs 3-4) ---------
        for m in live_slots:
            v = self.slots[m]
            fired = np.nonzero(v["rc_upserts"] >= self.min_num_upserts)[0]
            for pruner in fired.tolist():
                rc = v["rc"][pruner]
                min_stake = min(int(self.stakes[pruner]),
                                int(self.stakes[v["origin"]]))
                min_ingress_stake = int(
                    np.float64(min_stake)
                    * np.float64(self.prune_stake_threshold))
                order = sorted(rc.items(),
                               key=lambda kv: (-kv[1][0], -kv[1][1], kv[0]))
                cum = 0
                for pos, (src, (_, stake)) in enumerate(order):
                    if (pos >= self.min_ingress_nodes
                            and cum >= min_ingress_stake
                            and src != v["origin"]):
                        prunes_sent += 1
                        v["m"] += 1
                        node_prune_sent[pruner] += 1
                        node_prune_recv[src] += 1
                        # prune apply: every shared slot of src that
                        # points at the pruner gets the per-value bit
                        for slot in range(s):
                            if int(self.active[src, slot]) == pruner:
                                v["pruned"][src, slot] = True
                    cum += stake
                v["rc"][pruner] = dict()
                v["rc_upserts"][pruner] = 0

        # ---- shared rotation (one schedule for every value) -------------
        b_rot = round_basis(self.impair_seed, it, SALT_TRAFFIC_ROT)
        b_rc = round_basis(self.impair_seed, it, SALT_TRAFFIC_RCLASS)
        b_rm = round_basis(self.impair_seed, it, SALT_TRAFFIC_RMEMBER)
        nodes_u = np.arange(n, dtype=np.uint32)[:, None]
        tries_u = np.arange(self.rot_tries, dtype=np.uint32)[None, :]
        u_rot = u01_arr(node_u32_arr(np.uint32(b_rot),
                                     np.arange(n, dtype=np.uint32)))
        cands = class_draw_arr(
            self.tables,
            u01_arr(edge_u32_arr(np.uint32(b_rc), nodes_u, tries_u)),
            u01_arr(edge_u32_arr(np.uint32(b_rm), nodes_u, tries_u)))
        for node in range(n):
            if not (u_rot[node] < self.rot_prob):
                continue
            chosen = -1
            row = self.active[node]
            for t in range(self.rot_tries):
                c = int(cands[node, t])
                if c != node and not (row == c).any():
                    chosen = c
                    break
            if chosen < 0:
                continue
            cnt = int((row < n).sum())
            if cnt >= s:
                self.active[node, :-1] = row[1:].copy()
                self.active[node, -1] = chosen
                for m in live_slots:
                    pr = self.slots[m]["pruned"]
                    pr[node, :-1] = pr[node, 1:].copy()
                    pr[node, -1] = False
            else:
                self.active[node, cnt] = chosen

        # ---- stall / retirement / recycle -------------------------------
        records = []
        retired = converged = 0
        for m in live_slots:
            v = self.slots[m]
            if v["birth"] == it or progress[m] > 0:
                v["stall"] = 0
            else:
                v["stall"] += 1
            holders = int(v["holder"].sum())
            full = holders == n
            if full or v["stall"] >= self.stall_rounds:
                records.append(retire_record(
                    v["vid"], v["origin"], v["birth"], it, holders, n,
                    v["m"], full,
                    int(v["hop"][v["holder"]].sum()),
                    rescued=v["rescued"], qdrops=v["qdrop"]))
                retired += 1
                converged += int(full)
                self.slots[m] = None
        live = sum(sl is not None for sl in self.slots)

        # ---- adaptive direction switch (end of round, survivors only) ---
        switched = 0
        if self.adaptive:
            for m in range(self.mv):
                v = self.slots[m]
                if v is None:
                    continue
                new_on = switch_update(int(v["holder"].sum()), n, v["pull"],
                                       self.adapt_thr, self.adapt_hyst)
                if new_on and not v["pull"]:
                    switched += 1
                v["pull"] = new_on

        return TrafficRound(
            injected=injected, inject_dropped=inject_dropped, live=live,
            sends=sends, deferred=deferred, failed_target=failed_target,
            suppressed=suppressed, dropped=dropped, arrived=arrived,
            queue_dropped=queue_dropped, accepted=n_accepted,
            delivered=delivered, redundant=redundant,
            prunes_sent=prunes_sent, retired=retired, converged=converged,
            hop_clamped=hop_clamped,
            qdepth_max=int(node_deferred.max()) if n else 0,
            inflow_max=int(ingress_used.max()) if n else 0,
            records=records, node_deferred=node_deferred,
            node_queue_dropped=node_qdrop,
            node_sent=node_sent, node_recv=node_recv,
            node_prune_sent=node_prune_sent,
            node_prune_recv=node_prune_recv,
            node_delivered=node_delivered, node_lat_sum=node_lat_sum,
            node_rescued=node_rescued,
            pull_sent=pull_sent, pull_deferred=pull_deferred,
            pull_failed_target=pull_failed_target,
            pull_suppressed=pull_suppressed, pull_dropped=pull_dropped,
            pull_arrived=pull_arrived, pull_queue_dropped=pull_qdropped,
            pull_served=pull_served, pull_responses=pull_responses,
            pull_rescued=pull_rescued_cnt,
            pull_active_values=pull_active_values,
            switched_to_pull=switched)
