"""Protocol flight-recorder tests (obs/trace.py, stats/edges.py, ISSUE 3):
engine trace-row invariants and zero-bit-impact, writer/loader round-trips
with resume-safe segment merging, oracle-vs-engine trace parity under
faults, the CLI --trace-dir wiring on every run path, and the
--trace-dir + --resume composition regression."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.identity import (NodeIndex, get_stake_bucket,
                                     pubkey_new_unique)
from gossip_sim_tpu.obs.trace import (ARRAY_SPECS, TRACE_CANDIDATE,
                                      TRACE_DROPPED, TRACE_SCHEMA,
                                      OracleTraceCollector, TraceWriter,
                                      block_from_engine_rows, load_trace,
                                      validate_trace_dir,
                                      validate_trace_manifest)
from gossip_sim_tpu.stats import edges as E


def _engine_setup(n=60, seed=3, o=1, **kw):
    rng = np.random.default_rng(seed)
    stakes = rng.choice(np.arange(1, 5000), n, replace=False).astype(
        np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=n, warm_up_rounds=0, **kw).validate()
    origins = jnp.arange(o, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(7), tables, origins, params)
    return tables, params, origins, state


# --------------------------------------------------------------------------
# stats/edges.py unit tests on crafted arrays
# --------------------------------------------------------------------------

class TestEdgeAccounting:
    def test_build_delivery_tree_accepts_consistent_and_rejects_broken(self):
        dist = np.array([0, 1, 2, -1])
        first = np.array([-1, 0, 1, -1])
        parent, ok = E.build_delivery_tree(first, dist, origin=0)
        assert ok and parent.tolist() == [-1, 0, 1, -1]
        # wrong hop gap: node 2 claims first delivery from hop-0 node
        bad = np.array([-1, 0, 0, -1])
        _, ok = E.build_delivery_tree(bad, dist, origin=0)
        assert not ok
        # reached node with no recorded first delivery
        missing = np.array([-1, 0, -1, -1])
        _, ok = E.build_delivery_tree(missing, dist, origin=0)
        assert not ok

    def test_explain_stranded_classifies_every_path(self):
        # origin 0; node 3 stranded with four distinct failure paths; node 2
        # stranded with no potential senders at all
        n, s, f = 5, 2, 2
        active = np.full((n, s), -1)
        pruned = np.zeros((n, s), bool)
        peers = np.full((n, f), -1)
        code = np.zeros((n, f), np.int8)
        dist = np.array([0, 1, -1, -1, 1])
        failed = np.zeros(n, bool)
        active[0] = [3, 1]      # reached; pushed to 3 but the edge dropped
        peers[0] = [3, 1]
        code[0] = [TRACE_DROPPED, TRACE_CANDIDATE]
        active[1] = [3, -1]     # slot pruned for this origin
        pruned[1, 0] = True
        active[2] = [3, -1]     # sender itself unreached
        active[4] = [3, 0]      # valid slot but fanout-truncated
        peers[4] = [0, -1]
        code[4] = [TRACE_CANDIDATE, 0]

        out = E.explain_stranded(active, pruned, peers, code, dist, failed,
                                 origin=0)
        by_node = {e["node"]: e for e in out}
        assert set(by_node) == {2, 3}
        assert by_node[2]["summary"] == {E.CAUSE_NO_SENDERS: 1}
        s3 = by_node[3]["summary"]
        assert s3 == {E.CAUSE_DROPPED: 1, E.CAUSE_PRUNED: 1,
                      E.CAUSE_SENDER_UNREACHED: 1,
                      E.CAUSE_FANOUT_TRUNCATED: 1}
        causes = {(c["sender"], c["cause"]) for c in by_node[3]["causes"]}
        assert causes == {(0, E.CAUSE_DROPPED), (1, E.CAUSE_PRUNED),
                          (2, E.CAUSE_SENDER_UNREACHED),
                          (4, E.CAUSE_FANOUT_TRUNCATED)}

    def test_redundant_edges_and_diff(self):
        peers = np.array([[1, 2], [2, -1], [-1, -1]])
        code = np.array([[1, 1], [1, 0], [0, 0]], np.int8)
        dist = np.array([0, 1, 1])
        first = np.array([-1, 0, 0])   # 2's first sender is 0, so 1->2 is
        red = E.redundant_edge_counts(peers, code, dist, first, 3)
        assert red == {(1, 2): 1}
        d = E.diff_delivered(peers, code, dist,
                             peers, np.zeros_like(code), dist, 3)
        assert d["n_a"] == 3 and d["n_b"] == 0 and len(d["only_a"]) == 3


# --------------------------------------------------------------------------
# engine trace rows
# --------------------------------------------------------------------------

class TestEngineTraceRows:
    ROUNDS = 30   # long enough to cross the min_num_upserts prune threshold

    @pytest.fixture(scope="class")
    def traced(self):
        tables, params, origins, state = _engine_setup(o=2)
        state, rows = run_rounds(params, tables, origins, state, self.ROUNDS,
                                 detail=True, trace=True)
        return params, jax.tree_util.tree_map(np.asarray, rows)

    def test_trace_rows_bit_identical_after_knob_swap_without_recompile(self):
        """ISSUE 4: flight-recorder rows from a warm executable (compiled
        for different knob values) match a fresh compile of the target
        values bit-for-bit — the trace capture itself is knob-dynamic."""
        from gossip_sim_tpu.engine import (clear_compile_cache,
                                           compiled_cache_size)

        warm_kw = dict(packet_loss_rate=0.3, impair_seed=5,
                       probability_of_rotation=0.4)
        target_kw = dict(packet_loss_rate=0.1, impair_seed=12,
                         probability_of_rotation=0.1)
        tables, params, origins, state = _engine_setup(o=2, **warm_kw)
        run_rounds(params, tables, origins, state, 6, detail=True,
                   trace=True)                              # compile carrier
        before = compiled_cache_size()
        tables, params, origins, state = _engine_setup(o=2, **target_kw)
        _, r_warm = run_rounds(params, tables, origins, state, 6,
                               detail=True, trace=True)
        r_warm = jax.tree_util.tree_map(np.asarray, r_warm)
        if before >= 0:
            assert compiled_cache_size() == before, "knob swap recompiled"
        clear_compile_cache()
        tables, params, origins, state = _engine_setup(o=2, **target_kw)
        _, r_cold = run_rounds(params, tables, origins, state, 6,
                               detail=True, trace=True)
        r_cold = jax.tree_util.tree_map(np.asarray, r_cold)
        for k in r_cold:
            np.testing.assert_array_equal(r_warm[k], r_cold[k], err_msg=k)

    def test_trace_flag_changes_no_simulation_bits(self):
        tables, params, origins, state = _engine_setup(o=2)
        s1, r1 = run_rounds(params, tables, origins, state, 6, detail=True,
                            trace=True)
        tables, params, origins, state = _engine_setup(o=2)
        s2, r2 = run_rounds(params, tables, origins, state, 6, detail=True)
        r1 = jax.tree_util.tree_map(np.asarray, r1)
        r2 = jax.tree_util.tree_map(np.asarray, r2)
        for k in r2:
            np.testing.assert_array_equal(r1[k], r2[k], err_msg=k)
        for f in s2._fields:
            np.testing.assert_array_equal(np.asarray(getattr(s1, f)),
                                          np.asarray(getattr(s2, f)),
                                          err_msg=f)

    def test_first_delivery_and_tree(self, traced):
        _, rows = traced
        for r in range(self.ROUNDS):
            for col in range(2):
                dist = rows["dist"][r, col]
                first = rows["trace_first"][r, col]
                m = dist > 0
                assert (first[m] >= 0).all()
                assert (dist[first[m]] + 1 == dist[m]).all()
                origin = col  # origins were arange(2)
                _, ok = E.build_delivery_tree(first, dist, origin)
                assert ok, (r, col)
                # the shared edge-list form: one row per reached non-origin
                # node, hop == receiver distance, sender one hop closer
                fd = E.first_delivery_edges(first, dist)
                assert fd.shape[0] == int(m.sum())
                assert (fd[:, 2] == dist[fd[:, 1]]).all()
                assert (dist[fd[:, 0]] + 1 == fd[:, 2]).all()

    def test_delivered_edges_match_m_and_coverage(self, traced):
        _, rows = traced
        for r in range(self.ROUNDS):
            for col in range(2):
                dist = rows["dist"][r, col]
                edges = E.delivered_edges(rows["trace_peers"][r, col],
                                          rows["trace_code"][r, col], dist)
                assert edges.shape[0] == rows["delivered"][r, col]
                # delivered targets are reached
                assert (dist[edges[:, 1]] >= 0).all()

    def test_prune_pairs_match_prunes_sent(self, traced):
        _, rows = traced
        total = 0
        for r in range(self.ROUNDS):
            for col in range(2):
                pairs = (rows["trace_prune_src"][r, col] >= 0).sum()
                assert pairs == rows["prunes_sent"][r, col]
                total += int(pairs)
        assert total > 0, "run too short to exercise prune capture"

    def test_rotation_events_recorded(self, traced):
        _, rows = traced
        rot = rows["trace_rot"]
        assert (rot >= -1).all()
        assert (rot >= 0).any(), "no rotation event in 30 rounds"
        # a rotation event's peer lands in the newest slot of the next
        # round's active snapshot (full rows shift left)
        act = rows["trace_active"]
        for r in range(self.ROUNDS - 1):
            o_idx, n_idx = np.nonzero(rot[r] >= 0)
            for o, nd in zip(o_idx, n_idx):
                assert rot[r, o, nd] in act[r + 1, o, nd]

    def test_prune_capture_truncation_is_flagged(self, tmp_path):
        """A tiny trace_prune_cap forces truncation; the writer must flag
        the affected rounds in the manifest instead of dropping silently."""
        tables, params, origins, state = _engine_setup(
            o=1, trace_prune_cap=1)
        state, rows = run_rounds(params, tables, origins, state, self.ROUNDS,
                                 detail=True, trace=True)
        rows = jax.tree_util.tree_map(np.asarray, rows)
        assert (rows["prunes_sent"] > 1).any(), "need a >1-prune round"
        w = TraceWriter(str(tmp_path), backend="tpu",
                        num_nodes=params.num_nodes,
                        push_fanout=params.push_fanout,
                        active_set_size=params.active_set_size,
                        prune_cap=params.prune_cap, origins=[0],
                        origin_pubkeys=["o"], seed=0, warm_up_rounds=0,
                        iterations=self.ROUNDS)
        seg = w.add_block(0, block_from_engine_rows(rows))
        assert seg["truncated_prune_rounds"], "truncation not flagged"


# --------------------------------------------------------------------------
# writer / loader
# --------------------------------------------------------------------------

class TestWriterLoader:
    def _write(self, tmp_path, rounds=8, start=0, n=40):
        tables, params, origins, state = _engine_setup(n=n, o=1)
        state, rows = run_rounds(params, tables, origins, state,
                                 rounds + start, detail=True, trace=True)
        rows = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[start:], rows)
        w = TraceWriter(str(tmp_path), backend="tpu", num_nodes=n,
                        push_fanout=params.push_fanout,
                        active_set_size=params.active_set_size,
                        prune_cap=params.prune_cap, origins=[0],
                        origin_pubkeys=["pk0"], seed=7,
                        warm_up_rounds=start, iterations=rounds + start)
        return w, block_from_engine_rows(rows)

    def test_round_trip_and_validation(self, tmp_path):
        w, block = self._write(tmp_path)
        w.add_block(0, {k: v[:4] for k, v in block.items()})
        w.add_block(4, {k: v[4:] for k, v in block.items()})
        m = w.finalize()
        assert m["schema"] == TRACE_SCHEMA
        assert validate_trace_manifest(m) == []
        assert validate_trace_dir(str(tmp_path)) == []
        tr = load_trace(str(tmp_path))
        assert len(tr) == 8 and tr.rounds.tolist() == list(range(8))
        assert not tr.gaps
        for name in ARRAY_SPECS:
            np.testing.assert_array_equal(
                tr.arrays[name],
                block[name].astype(tr.arrays[name].dtype), err_msg=name)
        # convenience accessors
        assert tr.col_of(0) == 0
        assert set(tr.at(3)) == set(ARRAY_SPECS)
        with pytest.raises(KeyError):
            tr.pos_of(99)

    def test_v1_trace_still_readable(self, tmp_path):
        """ISSUE 5: trace schema v2 must keep v1 captures loadable — a v1
        manifest (no gossip_mode/pull_slots/pull arrays) validates and
        loads with the base array set."""
        import json

        from gossip_sim_tpu.obs.trace import (MANIFEST_NAME,
                                              TRACE_SCHEMA_V1)

        w, block = self._write(tmp_path)
        w.add_block(0, block)
        w.finalize()
        mpath = str(tmp_path / MANIFEST_NAME)
        with open(mpath) as f:
            m = json.load(f)
        # rewrite as a v1 manifest (what a pre-pull writer produced)
        m["schema"] = TRACE_SCHEMA_V1
        for key in ("gossip_mode", "pull_slots", "pull_codes"):
            m.pop(key, None)
        with open(mpath, "w") as f:
            json.dump(m, f)
        assert validate_trace_manifest(m) == []
        assert validate_trace_dir(str(tmp_path)) == []
        tr = load_trace(str(tmp_path))
        assert set(tr.arrays) == set(ARRAY_SPECS)
        assert len(tr) == 8

    def test_overlapping_segment_replaced_not_duplicated(self, tmp_path):
        w, block = self._write(tmp_path)
        w.add_block(0, {k: v[:6] for k, v in block.items()})
        # a resume re-running the same block overwrites, never duplicates
        w.add_block(0, {k: v[:6] for k, v in block.items()})
        assert len(w.manifest["segments"]) == 1
        # a partially-overlapping rewrite replaces the stale segment (the
        # new capture wins; no round is ever present twice)
        w.add_block(2, {k: v[2:] for k, v in block.items()})
        assert len(w.manifest["segments"]) == 1
        tr = load_trace(str(tmp_path))
        assert tr.rounds.tolist() == list(range(2, 8))
        counts = np.bincount(tr.rounds)
        assert (counts[counts > 0] == 1).all()

    def test_mismatched_manifest_replaced(self, tmp_path):
        w, block = self._write(tmp_path)
        w.add_block(0, block)
        # same dir, different seed -> prior segments must not be merged
        w2 = TraceWriter(str(tmp_path), backend="tpu", num_nodes=40,
                         push_fanout=6, active_set_size=12,
                         prune_cap=80, origins=[0], origin_pubkeys=["pk0"],
                         seed=99, warm_up_rounds=0, iterations=8)
        assert w2.manifest["segments"] == []

    def test_writer_rejects_clusters_beyond_int16_ids(self, tmp_path):
        """Node ids are stored int16; the engine shares the 32767 cap but
        the oracle has none, so the writer must refuse rather than let ids
        wrap into sentinel space."""
        with pytest.raises(ValueError, match="int16"):
            TraceWriter(str(tmp_path), backend="oracle", num_nodes=40000,
                        push_fanout=6, active_set_size=12, prune_cap=100,
                        origins=[0], origin_pubkeys=["pk0"], seed=0,
                        warm_up_rounds=0, iterations=1)

    def test_validation_catches_corruption(self, tmp_path):
        w, block = self._write(tmp_path)
        w.add_block(0, block)
        w.finalize()
        m_path = os.path.join(str(tmp_path), "manifest.json")
        with open(m_path) as f:
            m = json.load(f)
        seg_file = m["segments"][0]["file"]
        os.unlink(os.path.join(str(tmp_path), seg_file))
        assert any("missing" in p for p in validate_trace_dir(str(tmp_path)))
        m["schema"] = "bogus"
        assert any("schema" in p for p in validate_trace_manifest(m))


# --------------------------------------------------------------------------
# oracle-vs-engine trace parity (forced active sets, under faults)
# --------------------------------------------------------------------------

class TestOracleEngineTraceParity:
    """With the oracle's active sets forced to the engine's sampled ones
    and rotation off, both backends' flight recorders must log identical
    distances, first-delivery senders, delivered edge sets and prune pairs
    — including under packet loss + churn + a partition, which exercises
    every outcome code."""

    N = 256
    ROUNDS = 26   # past min_num_upserts so prune pairs get compared too
    SEED = 21
    KNOBS = dict(packet_loss_rate=0.15, churn_fail_rate=0.02,
                 churn_recover_rate=0.25, partition_at=2, heal_at=5)

    def test_trace_parity_under_faults(self):
        from gossip_sim_tpu.faults import FaultInjector
        from gossip_sim_tpu.oracle.cluster import Cluster, Node

        n = self.N
        rng = np.random.default_rng(17)
        stakes_arr = rng.choice(np.arange(1, 50 * n), size=n,
                                replace=False).astype(np.int64) * 10**9
        accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)
        tables = make_cluster_tables(stakes_np)
        params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                              warm_up_rounds=0, impair_seed=self.SEED,
                              **self.KNOBS).validate()
        origins = jnp.asarray([0], jnp.int32)
        state = init_state(jax.random.PRNGKey(11), tables, origins, params)

        stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
        nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
        origin_pk = index.pubkeys[0]
        active = np.asarray(state.active[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            node.active_set.entries[bucket].peers = {
                index.pubkeys[j]: {index.pubkeys[j]}
                for j in active[i] if j < n}
        node_map = {nd.pubkey: nd for nd in nodes}
        cluster = Cluster(params.push_fanout)
        impair = FaultInjector(index, seed=self.SEED, **self.KNOBS)
        collector = OracleTraceCollector(
            index, origin_pk, push_fanout=params.push_fanout,
            active_set_size=params.active_set_size,
            prune_cap=params.prune_cap)

        state, rows = run_rounds(params, tables, origins, state, self.ROUNDS,
                                 trace=True)
        rows = jax.tree_util.tree_map(np.asarray, rows)
        for r in range(self.ROUNDS):
            impair.begin_round(r)
            impair.churn_step(r, node_map, cluster.failed_nodes)
            collector.begin_round(cluster, node_map)
            cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
            cluster.consume_messages(origin_pk, nodes)
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)
            cluster.prune_connections(node_map, stakes_map)
            collector.end_round(r, cluster, node_map, [])
        start, block = collector.flush()
        assert start == 0

        saw_drop = saw_prune = False
        for r in range(self.ROUNDS):
            dist_e, dist_o = rows["dist"][r, 0], block["dist"][r, 0]
            np.testing.assert_array_equal(dist_e, dist_o,
                                          err_msg=f"dist round {r}")
            np.testing.assert_array_equal(
                rows["trace_first"][r, 0], block["first_src"][r, 0],
                err_msg=f"first_src round {r}")
            np.testing.assert_array_equal(
                rows["failed_mask"][r, 0], block["failed"][r, 0],
                err_msg=f"failed round {r}")
            edges_e = E.delivered_edges(rows["trace_peers"][r, 0],
                                        rows["trace_code"][r, 0], dist_e)
            edges_o = E.delivered_edges(block["peers"][r, 0],
                                        block["code"][r, 0], dist_o)
            assert (set(E.edge_keys(edges_e, n).tolist())
                    == set(E.edge_keys(edges_o, n).tolist())), r
            saw_drop |= bool((rows["trace_code"][r, 0] == TRACE_DROPPED)
                             .any())
            pairs_e = {(int(s), int(d)) for s, d in zip(
                rows["trace_prune_src"][r, 0], rows["trace_prune_dst"][r, 0])
                if s >= 0}
            pairs_o = {(int(s), int(d)) for s, d in zip(
                block["prune_src"][r, 0], block["prune_dst"][r, 0])
                if s >= 0}
            assert pairs_e == pairs_o, f"prune pairs diverge round {r}"
            saw_prune |= bool(pairs_e)
        assert saw_drop, "loss regime never exercised the dropped code"
        assert saw_prune, "run too short to compare prune pairs"


# --------------------------------------------------------------------------
# CLI wiring + resume composition
# --------------------------------------------------------------------------

class TestCliTrace:
    N = 40
    BASE = ["--num-synthetic-nodes", "40", "--seed", "7"]

    def _main(self, extra):
        from gossip_sim_tpu.cli import main
        return main(self.BASE + extra)

    def test_tpu_trace_end_to_end(self, tmp_path):
        d = str(tmp_path / "trace")
        rc = self._main(["--iterations", "12", "--warm-up-rounds", "4",
                         "--trace-dir", d])
        assert rc == 0
        assert validate_trace_dir(d) == []
        tr = load_trace(d)
        assert tr.manifest["backend"] == "tpu"
        assert len(tr) == 8 and int(tr.rounds[0]) == 4
        origin = tr.origins[0]
        for t in range(len(tr)):
            _, ok = E.build_delivery_tree(tr.arrays["first_src"][t, 0],
                                          tr.arrays["dist"][t, 0], origin)
            assert ok
            stranded = int(((tr.arrays["dist"][t, 0] < 0)
                            & ~tr.arrays["failed"][t, 0]).sum())
            expl = E.explain_stranded(
                tr.arrays["active"][t, 0], tr.arrays["pruned"][t, 0],
                tr.arrays["peers"][t, 0], tr.arrays["code"][t, 0],
                tr.arrays["dist"][t, 0], tr.arrays["failed"][t, 0], origin)
            assert len(expl) == stranded

    def test_oracle_trace_end_to_end(self, tmp_path):
        d = str(tmp_path / "trace")
        rc = self._main(["--iterations", "8", "--warm-up-rounds", "2",
                         "--backend", "oracle", "--trace-dir", d])
        assert rc == 0
        assert validate_trace_dir(d) == []
        tr = load_trace(d)
        assert tr.manifest["backend"] == "oracle"
        assert len(tr) == 6
        for t in range(len(tr)):
            _, ok = E.build_delivery_tree(tr.arrays["first_src"][t, 0],
                                          tr.arrays["dist"][t, 0],
                                          tr.origins[0])
            assert ok

    def test_trace_composes_with_resume(self, tmp_path):
        """Regression (ISSUE 3 satellite): a checkpoint restart must append
        the remaining rounds to the trace without duplicating or losing
        rounds already traced — the stitched trace equals the full run's."""
        from gossip_sim_tpu.identity import reset_unique_pubkeys

        full = str(tmp_path / "full")
        split = str(tmp_path / "split")
        ck = str(tmp_path / "ck.npz")
        # the synthetic cluster draws from the process-global unique-pubkey
        # counter: reset before each run so all three see the same cluster
        reset_unique_pubkeys()
        rc = self._main(["--iterations", "12", "--warm-up-rounds", "2",
                         "--trace-dir", full])
        assert rc == 0
        reset_unique_pubkeys()
        rc = self._main(["--iterations", "7", "--warm-up-rounds", "2",
                         "--trace-dir", split, "--checkpoint-path", ck])
        assert rc == 0
        reset_unique_pubkeys()
        rc = self._main(["--iterations", "12", "--warm-up-rounds", "2",
                         "--trace-dir", split, "--resume", ck])
        assert rc == 0
        a, b = load_trace(full), load_trace(split)
        assert len(b.manifest["segments"]) == 2
        assert not b.gaps
        np.testing.assert_array_equal(a.rounds, b.rounds)
        for name in ARRAY_SPECS:
            np.testing.assert_array_equal(a.arrays[name], b.arrays[name],
                                          err_msg=name)

    @pytest.mark.slow  # tier-1 budget; tools/trace_smoke gate covers this
    def test_batched_origin_rank_sweep_traces_all_columns(self, tmp_path):
        d = str(tmp_path / "trace")
        rc = self._main(["--iterations", "8", "--warm-up-rounds", "2",
                         "--test-type", "origin-rank",
                         "--num-simulations", "2", "--origin-rank", "1", "3",
                         "--trace-dir", d])
        assert rc == 0
        assert validate_trace_dir(d) == []
        tr = load_trace(d)
        assert len(tr.origins) == 2
        for col, origin in enumerate(tr.origins):
            for t in range(len(tr)):
                _, ok = E.build_delivery_tree(
                    tr.arrays["first_src"][t, col],
                    tr.arrays["dist"][t, col], origin)
                assert ok, (t, col)

    def test_generic_sweep_writes_per_sim_subdirs(self, tmp_path):
        d = str(tmp_path / "trace")
        rc = self._main(["--iterations", "6", "--warm-up-rounds", "2",
                         "--test-type", "rotate-probability",
                         "--num-simulations", "2", "--step-size", "0.1",
                         "--trace-dir", d])
        assert rc == 0
        for sub in ("sim000", "sim001"):
            assert validate_trace_dir(os.path.join(d, sub)) == []

    @pytest.mark.slow  # tier-1 budget; tools/trace_smoke gate covers this
    def test_all_origins_traces_sampled_origins(self, tmp_path):
        d = str(tmp_path / "trace")
        rc = self._main(["--iterations", "6", "--warm-up-rounds", "2",
                         "--all-origins", "--trace-origins", "2",
                         "--trace-dir", d])
        assert rc == 0
        assert validate_trace_dir(d) == []
        tr = load_trace(d)
        assert tr.origins == [0, 1] and len(tr) == 4
        for col, origin in enumerate(tr.origins):
            for t in range(len(tr)):
                _, ok = E.build_delivery_tree(
                    tr.arrays["first_src"][t, col],
                    tr.arrays["dist"][t, col], origin)
                assert ok, (t, col)

    def test_trace_flags_parse_into_config(self):
        from gossip_sim_tpu.cli import build_parser, config_from_args

        cfg = config_from_args(build_parser().parse_args(
            ["--trace-dir", "/tmp/t", "--trace-origins", "2",
             "--trace-prune-cap", "512"]))
        assert cfg.trace_dir == "/tmp/t"
        assert cfg.trace_origins == 2
        assert cfg.trace_prune_cap == 512
        # the cap reaches the engine: EngineParams resolves it verbatim
        assert EngineParams(num_nodes=100,
                            trace_prune_cap=512).prune_cap == 512
        assert EngineParams(num_nodes=100).prune_cap == 1600

    def test_no_measured_rounds_warns_and_writes_nothing(self, tmp_path,
                                                         caplog):
        d = str(tmp_path / "trace")
        rc = self._main(["--iterations", "3", "--warm-up-rounds", "5",
                         "--trace-dir", d])
        assert rc == 0
        assert not os.path.exists(os.path.join(d, "manifest.json"))
