"""Adaptive push-pull subsystem tests (adaptive.py; ISSUE 11).

Five contracts:

* **Mode gating, zero bit-impact** — ``gossip_mode="push"`` and ``"pull"``
  emit bit-identical rows/state whatever the adaptive knobs say (the
  switch exists only in the adaptive graph), and adaptive mode itself
  starts push-only (the direction bit is False until coverage crosses the
  threshold).
* **Switch semantics** — the direction bit activates one round after push
  coverage crosses the threshold, the hysteresis window gates the flip
  back, and gated rounds report the identical zero pull counters an
  off-interval round does.
* **Oracle parity** — at 1k nodes under packet loss AND churn the
  sort-routed engine and the loop-based AdaptiveOracle agree bit-for-bit
  on the direction bit, switch rounds, pull counters and rescue hops.
* **Traffic composition** — per-value pull rescues in the traffic engine
  are bit-exact vs TrafficOracle (counters, retirement records with
  terminal causes) and actually rescue starved values.
* **Compile-once / lanes** — an adaptive-threshold sweep reuses one
  compiled executable and is lane-batchable with per-lane bit-parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.adaptive import (AdaptiveOracle, switch_update,
                                     switch_update_arr)
from gossip_sim_tpu.constants import UNREACHED
from gossip_sim_tpu.engine import (EngineParams, clear_compile_cache,
                                   compiled_cache_size, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.identity import (NodeIndex, get_stake_bucket,
                                     pubkey_new_unique)
from gossip_sim_tpu.oracle.cluster import Cluster, Node


def _stakes(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.choice(np.arange(1, 50 * n), size=n,
                      replace=False).astype(np.int64) * 10**9


def _run_engine(params, n, seed=3, rounds=6, **kw):
    tables = make_cluster_tables(_stakes(n, seed))
    origins = jnp.arange(1, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(seed), tables, origins, params)
    state, rows = run_rounds(params, tables, origins, state, rounds, **kw)
    return state, jax.tree_util.tree_map(np.asarray, rows)


# --------------------------------------------------------------------------
# the switch rule itself
# --------------------------------------------------------------------------

class TestSwitchRule:
    def test_threshold_and_hysteresis_band(self):
        n = 1000
        # crossing up at >= thr * n
        assert switch_update(900, n, False, 0.9, 0.05)
        assert not switch_update(899, n, False, 0.9, 0.05)
        # inside the hysteresis band the bit holds its value
        assert switch_update(870, n, True, 0.9, 0.05)
        assert not switch_update(870, n, False, 0.9, 0.05)
        # below thr - hyst it drops
        assert not switch_update(849, n, True, 0.9, 0.05)

    def test_array_and_scalar_paths_agree(self):
        n = 777
        counts = np.arange(0, n + 1, 7, dtype=np.int64)
        for prev in (False, True):
            arr = switch_update_arr(counts, n, np.full(counts.shape, prev),
                                    0.83, 0.11)
            scal = np.array([switch_update(int(c), n, prev, 0.83, 0.11)
                             for c in counts])
            np.testing.assert_array_equal(arr, scal)

    def test_jnp_path_matches_numpy(self):
        n = 500
        counts = np.arange(0, n + 1, 13, dtype=np.int32)
        prev = (counts % 2) == 0
        a = switch_update_arr(counts, n, prev, 0.77, 0.07)
        b = np.asarray(switch_update_arr(jnp.asarray(counts), n,
                                         jnp.asarray(prev),
                                         np.float64(0.77), np.float64(0.07),
                                         jnp))
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------------------
# mode gating: zero bit-impact outside adaptive mode
# --------------------------------------------------------------------------

class TestModeGating:
    N = 128

    def test_push_mode_ignores_adaptive_knobs(self):
        """mode=push with adaptive knobs set emits bit-identical rows and
        state to the bare defaults — no switch exists in the graph."""
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0)
        explicit = base._replace(adaptive_switch_threshold=0.3,
                                 adaptive_switch_hysteresis=0.2)
        s1, r1 = _run_engine(base, self.N, rounds=5, detail=True)
        s2, r2 = _run_engine(explicit, self.N, rounds=5, detail=True)
        assert set(r1) == set(r2)
        assert "adaptive_pull_active" not in r1
        for k in r1:
            np.testing.assert_array_equal(r1[k], r2[k], err_msg=k)
        for f in s1._fields:
            np.testing.assert_array_equal(np.asarray(getattr(s1, f)),
                                          np.asarray(getattr(s2, f)),
                                          err_msg=f)
        assert not np.asarray(s1.adaptive_pull_on).any()

    def test_pull_modes_ignore_adaptive_knobs(self):
        """Fixed pull modes carry no switch either: stepping the adaptive
        knobs reuses the same executable and moves zero bits."""
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            gossip_mode="push-pull", pull_fanout=3)
        explicit = base._replace(adaptive_switch_threshold=0.2,
                                 adaptive_switch_hysteresis=0.1)
        assert base.static_part() == explicit.static_part()
        _, r1 = _run_engine(base, self.N, rounds=4, detail=True)
        _, r2 = _run_engine(explicit, self.N, rounds=4, detail=True)
        for k in r1:
            np.testing.assert_array_equal(r1[k], r2[k], err_msg=k)

    def test_adaptive_mode_validation(self):
        with pytest.raises(AssertionError):
            EngineParams(num_nodes=16, gossip_mode="adaptive",
                         adaptive_switch_threshold=0.0).validate()
        with pytest.raises(AssertionError):
            EngineParams(num_nodes=16, gossip_mode="adaptive",
                         adaptive_switch_hysteresis=0.95).validate()
        # traffic composes with push and adaptive, not fixed pull modes
        EngineParams(num_nodes=16, traffic_values=4,
                     gossip_mode="adaptive").validate()
        with pytest.raises(AssertionError):
            EngineParams(num_nodes=16, traffic_values=4,
                         gossip_mode="push-pull").validate()
        with pytest.raises(AssertionError):
            EngineParams(num_nodes=16, traffic_values=4,
                         gossip_mode="adaptive",
                         node_ingress_cap=1 << 20).validate()


# --------------------------------------------------------------------------
# switch semantics in the single-origin engine
# --------------------------------------------------------------------------

class TestAdaptiveEngine:
    N = 128

    def test_first_round_is_push_only_then_switches(self):
        """The direction bit starts False (round 0 is pure push); once the
        round's push coverage crosses the threshold the pull phase runs
        from the NEXT round on."""
        p = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                         gossip_mode="adaptive",
                         adaptive_switch_threshold=0.5,
                         adaptive_switch_hysteresis=0.1).validate()
        _, rows = _run_engine(p, self.N, rounds=5, detail=True)
        act = rows["adaptive_pull_active"][:, 0].astype(int)
        assert act[0] == 0
        assert rows["pull_requests"][0, 0] == 0
        # an unimpaired push run covers everything in round 0, so the bit
        # is on (and pull runs) from round 1 onward
        assert (act[1:] == 1).all()
        assert (rows["pull_requests"][1:, 0] > 0).all()
        assert rows["adaptive_switched"][0, 0] == 1

    def test_gated_round_matches_interval_gated_round(self):
        """A switch-gated pull round reports the identical zero counters
        and -1 trace slots an off-interval pull round does."""
        p = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                         gossip_mode="adaptive",
                         adaptive_switch_threshold=0.5).validate()
        _, rows = _run_engine(p, self.N, rounds=2, detail=True, trace=True)
        # round 0 is gated by the direction bit
        assert rows["pull_requests"][0, 0] == 0
        assert (rows["trace_pull_peers"][0, 0] == -1).all()
        assert (rows["trace_pull_code"][0, 0] == 0).all()
        assert (rows["pull_hop"][0, 0] == -1).all()


# --------------------------------------------------------------------------
# 1k-node oracle-vs-engine bit-exact parity under loss + churn
# --------------------------------------------------------------------------

class TestAdaptiveParity:
    """The acceptance gate: >= 1k nodes, shared seeds, forced-identical
    active sets, rotation off, packet loss AND churn active, adaptive
    mode — the direction bit, switch rounds, pull counters and rescue
    hops must match the AdaptiveOracle bit-for-bit every round."""

    N = 1024
    ROUNDS = 6
    SEED = 77
    KNOBS = dict(packet_loss_rate=0.15, churn_fail_rate=0.02,
                 churn_recover_rate=0.25)
    PULL = dict(pull_fanout=3, pull_interval=1, pull_bloom_fp_rate=0.25,
                pull_request_cap=3)
    ADAPT = dict(adaptive_switch_threshold=0.9,
                 adaptive_switch_hysteresis=0.05)

    def test_exact_parity_adaptive_under_faults(self):
        n = self.N
        stakes_arr = _stakes(n, seed=23)
        accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)

        tables = make_cluster_tables(stakes_np)
        params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                              warm_up_rounds=0, impair_seed=self.SEED,
                              gossip_mode="adaptive", **self.KNOBS,
                              **self.PULL, **self.ADAPT).validate()
        origins = jnp.asarray([0], jnp.int32)
        state = init_state(jax.random.PRNGKey(13), tables, origins, params)

        stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
        nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
        origin_pk = index.pubkeys[0]
        active = np.asarray(state.active[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            entry = node.active_set.entries[bucket]
            entry.peers = {index.pubkeys[j]: {index.pubkeys[j]}
                           for j in active[i] if j < n}
        node_map = {nd.pubkey: nd for nd in nodes}

        from gossip_sim_tpu.faults import FaultInjector
        cluster = Cluster(params.push_fanout)
        impair = FaultInjector(index, seed=self.SEED, **self.KNOBS)
        oracle = AdaptiveOracle(
            stakes_np, seed=self.SEED,
            pull_slots=params.pull_slots_resolved,
            packet_loss_rate=self.KNOBS["packet_loss_rate"],
            **self.PULL, **self.ADAPT)

        state, rows = run_rounds(params, tables, origins, state,
                                 self.ROUNDS, detail=True)
        rows = jax.tree_util.tree_map(np.asarray, rows)

        saw_on = saw_rescue = False
        for r in range(self.ROUNDS):
            impair.begin_round(r)
            impair.churn_step(r, node_map, cluster.failed_nodes)
            cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
            active_pre = bool(oracle.pull_active)
            cluster.run_pull(oracle, r, index, node_map)
            cluster.consume_messages(origin_pk, nodes)

            assert int(rows["adaptive_pull_active"][r, 0]) == int(
                active_pre), f"direction bit diverges at round {r}"
            sw = oracle.switch_rounds
            assert int(rows["adaptive_switched"][r, 0]) == int(
                bool(sw) and sw[-1][0] == r), f"switch event at round {r}"

            pr = cluster.pull
            assert rows["pull_requests"][r, 0] == pr.requests, f"round {r}"
            assert rows["pull_responses"][r, 0] == pr.responses, f"round {r}"
            assert rows["pull_misses"][r, 0] == pr.misses, f"round {r}"
            assert rows["pull_dropped"][r, 0] == pr.dropped, f"round {r}"
            assert rows["pull_rescued"][r, 0] == len(pr.rescued), f"round {r}"
            np.testing.assert_array_equal(
                rows["pull_hop"][r, 0], pr.pull_hop.astype(np.int32),
                err_msg=f"pull hops diverge at round {r}")

            dist_o = np.array(
                [-1 if cluster.distances[pk] == UNREACHED
                 else cluster.distances[pk] for pk in index.pubkeys])
            np.testing.assert_array_equal(
                rows["dist"][r, 0], dist_o,
                err_msg=f"push distances diverge at round {r}")

            saw_on |= active_pre
            saw_rescue |= len(pr.rescued) > 0
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)
            cluster.prune_connections(node_map, stakes_map)

        assert saw_on, "regime never flipped into the pull phase"
        assert saw_rescue, "regime never exercised an adaptive rescue"


# --------------------------------------------------------------------------
# traffic composition: per-value pull rescues (engine vs TrafficOracle)
# --------------------------------------------------------------------------

ADAPTIVE_PARITY_FIELDS = [
    "injected", "inject_dropped", "live", "sends", "deferred",
    "failed_target", "suppressed", "dropped", "arrived", "queue_dropped",
    "accepted", "delivered", "redundant", "prunes_sent", "retired",
    "converged", "hop_clamped", "qdepth_max", "inflow_max",
    "pull_sent", "pull_deferred", "pull_failed_target", "pull_suppressed",
    "pull_dropped", "pull_arrived", "pull_queue_dropped", "pull_served",
    "pull_responses", "pull_rescued", "pull_active_values",
    "switched_to_pull",
]


class TestTrafficAdaptiveParity:
    N = 120
    ROUNDS = 30
    KW = dict(traffic_values=6, traffic_rate=2, node_ingress_cap=24,
              node_egress_cap=32, traffic_stall_rounds=4,
              packet_loss_rate=0.1, churn_fail_rate=0.02,
              churn_recover_rate=0.25)

    def test_engine_matches_oracle_with_rescues(self):
        from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                                   init_traffic_state,
                                                   run_traffic_rounds)
        from gossip_sim_tpu.traffic import TrafficOracle, retire_record

        n = self.N
        stakes = _stakes(n, seed=3)
        p = EngineParams(num_nodes=n, warm_up_rounds=0,
                         gossip_mode="adaptive", impair_seed=7,
                         adaptive_switch_threshold=0.6,
                         adaptive_switch_hysteresis=0.1,
                         **self.KW).validate()
        tables = make_cluster_tables(stakes)
        tt = device_traffic_tables(stakes)
        st = init_traffic_state(stakes, p, seed=11)
        st, rows = run_traffic_rounds(p, tables, tt, st, self.ROUNDS)
        rows = jax.tree_util.tree_map(np.asarray, rows)

        orc = TrafficOracle(stakes, seed=11, impair_seed=7,
                            gossip_mode="adaptive",
                            adaptive_switch_threshold=0.6,
                            adaptive_switch_hysteresis=0.1, **self.KW)
        orecs = []
        for it in range(self.ROUNDS):
            tr = orc.run_round(it)
            orecs.extend(tr.records)
            for f in ADAPTIVE_PARITY_FIELDS:
                assert int(rows[f][it]) == int(getattr(tr, f)), \
                    f"round {it}: {f}"
        erecs = []
        for it in range(self.ROUNDS):
            for m in np.nonzero(rows["ret_mask"][it])[0]:
                g = lambda k: rows[k][it, m]
                erecs.append(retire_record(
                    int(g("ret_vid")), int(g("ret_origin")),
                    int(g("ret_birth")), it, int(g("ret_holders")), n,
                    int(g("ret_m")), bool(g("ret_full")),
                    int(g("ret_hops_sum")), rescued=int(g("ret_rescued")),
                    qdrops=int(g("ret_qdrop"))))
        assert erecs == orecs
        # the regime must actually exercise the healing path
        assert sum(r["rescued_by_pull"] for r in orecs) > 0
        assert any(r["cause"] == "rescued_by_pull" for r in orecs)
        # pull-phase values stop pushing: switch events happened
        assert rows["switched_to_pull"].sum() > 0

    def test_push_traffic_unaffected_by_adaptive_knobs(self):
        """mode=push traffic with adaptive knobs set is bit-identical to
        the bare push traffic engine (same static key, no rescue code)."""
        from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                                   init_traffic_state,
                                                   run_traffic_rounds)
        n = 100
        stakes = _stakes(n, seed=5)
        base = EngineParams(num_nodes=n, warm_up_rounds=0, impair_seed=2,
                            **self.KW).validate()
        knobbed = base._replace(adaptive_switch_threshold=0.1,
                                adaptive_switch_hysteresis=0.05)
        assert base.static_part() == knobbed.static_part()
        tables = make_cluster_tables(stakes)
        tt = device_traffic_tables(stakes)

        def run(p):
            st = init_traffic_state(stakes, p, seed=4)
            st, rows = run_traffic_rounds(p, tables, tt, st, 8)
            return st, jax.tree_util.tree_map(np.asarray, rows)

        s1, r1 = run(base)
        s2, r2 = run(knobbed)
        assert set(r1) == set(r2)
        assert "pull_sent" not in r1
        for k in r1:
            np.testing.assert_array_equal(r1[k], r2[k], err_msg=k)
        for f in s1._fields:
            np.testing.assert_array_equal(np.asarray(getattr(s1, f)),
                                          np.asarray(getattr(s2, f)),
                                          err_msg=f)
        assert not np.asarray(s1.v_pull).any()


# --------------------------------------------------------------------------
# compile-once + lane parity for the threshold sweep
# --------------------------------------------------------------------------

class TestAdaptiveSweeps:
    N = 96

    def test_threshold_sweep_compiles_once(self):
        p0 = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                          gossip_mode="adaptive",
                          adaptive_switch_threshold=0.5).validate()
        tables = make_cluster_tables(_stakes(self.N, 1))
        origins = jnp.arange(1, dtype=jnp.int32)
        clear_compile_cache()
        state = init_state(jax.random.PRNGKey(0), tables, origins, p0)
        state, _ = run_rounds(p0, tables, origins, state, 3)
        base = compiled_cache_size()
        for thr in (0.6, 0.75, 0.9):
            p = p0._replace(adaptive_switch_threshold=thr)
            state, rows = run_rounds(p, tables, origins, state, 3)
        assert compiled_cache_size() == base, \
            "threshold steps must reuse the compiled executable"

    def test_lane_sweep_matches_serial(self):
        from gossip_sim_tpu.engine import (broadcast_state, run_rounds_lanes,
                                           stack_knobs)
        thresholds = (0.4, 0.7, 0.95)
        p0 = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                          gossip_mode="adaptive",
                          adaptive_switch_hysteresis=0.1,
                          packet_loss_rate=0.2, impair_seed=5).validate()
        tables = make_cluster_tables(_stakes(self.N, 1))
        origins = jnp.arange(1, dtype=jnp.int32)
        init = init_state(jax.random.PRNGKey(2), tables, origins, p0)
        static = p0.static_part()
        params_k = [p0._replace(adaptive_switch_threshold=t)
                    for t in thresholds]
        lane_knobs = stack_knobs([p.knob_values() for p in params_k])
        lstates, lrows = run_rounds_lanes(
            static, tables, origins, broadcast_state(init, len(thresholds)),
            lane_knobs, 5)
        lrows = jax.tree_util.tree_map(np.asarray, lrows)
        for lane, p in enumerate(params_k):
            st = init_state(jax.random.PRNGKey(2), tables, origins, p)
            st, rows = run_rounds(p, tables, origins, st, 5)
            rows = jax.tree_util.tree_map(np.asarray, rows)
            for k in ("coverage", "pull_requests", "pull_rescued",
                      "adaptive_pull_active", "adaptive_switched", "m",
                      "rmr"):
                np.testing.assert_array_equal(
                    rows[k], lrows[k][:, lane],
                    err_msg=f"lane {lane} ({p.adaptive_switch_threshold}) "
                            f"{k}")


# --------------------------------------------------------------------------
# checkpoint v7: adaptive state round-trips and resumes bit-exactly
# --------------------------------------------------------------------------

class TestAdaptiveCheckpoint:
    def test_v7_traffic_adaptive_roundtrip_and_resume(self, tmp_path):
        from gossip_sim_tpu.checkpoint import (restore_traffic_state,
                                               save_traffic_state)
        from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                                   init_traffic_state,
                                                   run_traffic_rounds)

        n = 64
        stakes = _stakes(n, seed=9)
        p = EngineParams(num_nodes=n, warm_up_rounds=0,
                         gossip_mode="adaptive", traffic_values=4,
                         traffic_rate=1, node_ingress_cap=16,
                         adaptive_switch_threshold=0.5).validate()
        tables = make_cluster_tables(stakes)
        tt = device_traffic_tables(stakes)
        st = init_traffic_state(stakes, p, seed=6)
        st, _ = run_traffic_rounds(p, tables, tt, st, 6)
        # save BEFORE the straight continuation: the runner donates its
        # input state buffers
        path = str(tmp_path / "adaptive.npz")
        save_traffic_state(path, st, p, iteration=6)
        straight, rows_a = run_traffic_rounds(p, tables, tt, st, 4,
                                              start_it=6)
        restored, _, meta = restore_traffic_state(path, p)
        # current writer version (v8 as of ISSUE 17); the
        # adaptive arrays ride along in every later format
        assert meta["format_version"] >= 7
        assert meta["adaptive"]["adaptive_switch_threshold"] == 0.5
        resumed, rows_b = run_traffic_rounds(p, tables, tt, restored, 4,
                                             start_it=6)
        for f in straight._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(straight, f)),
                np.asarray(getattr(resumed, f)), err_msg=f)
        for k in rows_a:
            np.testing.assert_array_equal(np.asarray(rows_a[k]),
                                          np.asarray(rows_b[k]), err_msg=k)

    def test_sim_checkpoint_carries_direction_bit(self, tmp_path):
        from gossip_sim_tpu.checkpoint import (restore_sim_state,
                                               save_state)

        n = 64
        p = EngineParams(num_nodes=n, warm_up_rounds=0,
                         gossip_mode="adaptive",
                         adaptive_switch_threshold=0.5).validate()
        tables = make_cluster_tables(_stakes(n, 2))
        origins = jnp.arange(1, dtype=jnp.int32)
        st = init_state(jax.random.PRNGKey(1), tables, origins, p)
        st, _ = run_rounds(p, tables, origins, st, 3)
        assert np.asarray(st.adaptive_pull_on).any()
        path = str(tmp_path / "sim.npz")
        save_state(path, st, p, iteration=3)
        restored, _, meta = restore_sim_state(path, p)
        np.testing.assert_array_equal(np.asarray(restored.adaptive_pull_on),
                                      np.asarray(st.adaptive_pull_on))
        assert meta["adaptive"]["adaptive_switch_threshold"] == 0.5
