"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip sharding is validated on virtual CPU devices (no TPU pod needed);
the driver separately dry-runs the multichip path via __graft_entry__.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments force-register an accelerator PJRT plugin via
# sitecustomize and pin jax_platforms past the env var; override it at the
# config level before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from gossip_sim_tpu.identity import reset_unique_pubkeys  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_pubkey_counter():
    """Reference test fixtures assume the Pubkey::new_unique counter starts
    at 1 in each test."""
    reset_unique_pubkeys()
    yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy end-to-end tests excluded from the tier-1 "
        "'-m not slow' suite (still run by a plain pytest invocation)")
