"""TPU-engine tests: invariants, reference-golden behavior, and EXACT
multi-round parity against the CPU oracle with forced-identical active sets.

With rotation off, both backends are fully deterministic after
initialization, so distances, RMR counters, prune timing, prune pairs and
prune application must match bit-for-bit (SURVEY.md §4: exact parity
downstream of sampling)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.constants import UNREACHED
from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.engine.sampler import build_sampler_tables, sample_peers
from gossip_sim_tpu.identity import NodeIndex, get_stake_bucket, pubkey_new_unique
from gossip_sim_tpu.oracle.cluster import Cluster, Node


def _synthetic(n, seed=0, max_sol=1 << 16):
    rng = np.random.default_rng(seed)
    # distinct stakes -> no (score, stake) prune ties (tie-break orders
    # legitimately differ between backends; see engine/core.py docstring)
    stakes = rng.choice(np.arange(1, 50 * n), size=n, replace=False).astype(
        np.int64) * 1_000_000_000
    return stakes


def _init(n, n_origins=2, seed=7, **kw):
    stakes = _synthetic(n)
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=n, **kw)
    origins = jnp.arange(n_origins, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(seed), tables, origins, params)
    return stakes, tables, params, origins, state


class TestEngineBasics:
    def test_init_invariants(self):
        _, _, params, _, state = _init(100)
        active = np.asarray(state.active)
        n, s = params.num_nodes, params.active_set_size
        assert active.shape == (2, n, s)
        assert (active < n).all(), "entries fill fully at n=100"
        # no self-pushes, no duplicate peers within an entry
        for o in range(active.shape[0]):
            for i in range(n):
                row = active[o, i]
                assert i not in row
                assert len(set(row.tolist())) == s
        assert not np.asarray(state.pruned).any()

    def test_round_invariants(self):
        _, tables, params, origins, state = _init(100, warm_up_rounds=0)
        state, rows = run_rounds(params, tables, origins, state, 30)
        rc = np.asarray(state.rc_src)
        n = params.num_nodes
        members = rc < n
        # received cache rows stay sorted and duplicate-free
        assert (np.diff(rc, axis=-1) >= 0).all()
        inner = members[..., 1:] & members[..., :-1]
        assert (np.diff(rc, axis=-1)[inner] > 0).all()
        cov = np.asarray(rows["coverage"])
        assert (cov > 0.9).all()
        assert np.asarray(rows["rc_overflow"]).sum() == 0
        # origin never caches or gets pushed to: its own row stays empty
        for o, org in enumerate(np.asarray(origins)):
            assert not members[o, org].any()

    def test_prune_gate_and_rmr_convergence(self):
        """The MIN_NUM_UPSERTS=20 gate: no prunes until iteration 19, then
        RMR (which counts prune messages, gossip.rs:684-687) converges
        down — the reference's test_pruning / test_rmr shape
        (gossip_main.rs:1137-1152, gossip_stats.rs:2146-2154)."""
        _, tables, params, origins, state = _init(200, warm_up_rounds=0)
        state, rows = run_rounds(params, tables, origins, state, 100)
        prunes = np.asarray(rows["prunes_sent"])
        assert (prunes[:19] == 0).all()
        assert (prunes[19] > 0).all(), "every origin-sim fires at iter 19"
        rmr = np.asarray(rows["rmr"])
        assert (rmr[50:] .mean(axis=0) < rmr[:19].mean(axis=0)).all()

    def test_full_rotation_shifts_slots(self):
        _, tables, params, origins, state = _init(
            100, probability_of_rotation=1.0)
        before = np.asarray(state.active).copy()
        state, _ = run_rounds(params, tables, origins, state, 1)
        after = np.asarray(state.active)
        # p=1: every full entry swaps exactly one peer in, oldest out
        # (push_active_set.rs:153-186)
        assert (after[..., :-1] == before[..., 1:]).all()
        assert (after[..., -1] != before[..., -1]).any()

    def test_failure_injection(self):
        _, tables, params, origins, state = _init(
            200, warm_up_rounds=0, fail_at=2, fail_fraction=0.2)
        state, rows = run_rounds(params, tables, origins, state, 6)
        failed = np.asarray(state.failed)
        assert (failed.sum(axis=-1) == 40).all()
        cov = np.asarray(rows["coverage"])
        assert (cov[3:] < cov[0]).all(), "coverage drops after failure"
        # failed nodes are not counted as stranded (gossip.rs:334-344)
        stranded = np.asarray(rows["stranded"])
        assert (stranded[5] <= 200 - cov[5] * 200 + 1e-6).all()


class TestSampler:
    def test_class_distribution(self):
        buckets = np.repeat(np.arange(5), [10, 20, 5, 40, 25]).astype(np.int32)
        tables = build_sampler_tables(buckets)
        k = jnp.full((20000,), 3, jnp.int32)
        key = jax.random.PRNGKey(0)
        u = jax.random.uniform(key, (20000, 2), dtype=jnp.float32)
        peers = np.asarray(sample_peers(tables, k, u[:, 0], u[:, 1]))
        got = np.bincount(buckets[peers], minlength=5) / len(peers)
        counts = np.array([10, 20, 5, 40, 25], float)
        w = (np.minimum(np.arange(5), 3) + 1.0) ** 2
        expect = counts * w / (counts * w).sum()
        np.testing.assert_allclose(got, expect, atol=0.02)

    def test_within_class_uniform(self):
        buckets = np.zeros(50, np.int32)
        tables = build_sampler_tables(buckets)
        key = jax.random.PRNGKey(1)
        u = jax.random.uniform(key, (50000, 2), dtype=jnp.float32)
        peers = np.asarray(sample_peers(
            tables, jnp.zeros(50000, jnp.int32), u[:, 0], u[:, 1]))
        freq = np.bincount(peers, minlength=50) / 50000
        np.testing.assert_allclose(freq, 1 / 50, atol=0.01)


class TestOracleParity:
    """Force the oracle's active sets to the engine's sampled ones, turn
    rotation off, and demand bit-exact evolution for 25 rounds."""

    N = 40
    ROUNDS = 25
    PARAMS: dict = {"inbound_cap": 16}

    @pytest.fixture()
    def pair(self):
        n = self.N
        stakes_arr = _synthetic(n, seed=3)
        accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)

        tables = make_cluster_tables(stakes_np)
        params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                              warm_up_rounds=0, **self.PARAMS)
        origin_idx = 0
        origins = jnp.asarray([origin_idx], jnp.int32)
        state = init_state(jax.random.PRNGKey(11), tables, origins, params)

        # oracle cluster with the engine's exact active sets
        stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
        nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
        origin_pk = index.pubkeys[origin_idx]
        active = np.asarray(state.active[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            entry = node.active_set.entries[bucket]
            entry.peers = {index.pubkeys[j]: {index.pubkeys[j]}
                           for j in active[i] if j < n}
        return (index, stakes_map, nodes, origin_pk, origin_idx,
                tables, params, origins, state)

    def test_exact_parity(self, pair):
        (index, stakes_map, nodes, origin_pk, origin_idx,
         tables, params, origins, state) = pair
        n = self.N
        node_map = {nd.pubkey: nd for nd in nodes}
        cluster = Cluster(params.push_fanout)

        state, rows = run_rounds(params, tables, origins, state,
                                 self.ROUNDS, detail=True)
        dist_e = np.asarray(rows["dist"])[:, 0]        # [rounds, N], -1 unreached
        m_e = np.asarray(rows["m"])[:, 0]
        n_e = np.asarray(rows["n"])[:, 0]
        prunes_e = np.asarray(rows["prunes_sent"])[:, 0]

        for r in range(self.ROUNDS):
            cluster.run_gossip(origin_pk, stakes_map, node_map)
            cluster.consume_messages(origin_pk, nodes)
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)
            dist_o = np.array(
                [-1 if cluster.distances[pk] == UNREACHED
                 else cluster.distances[pk] for pk in index.pubkeys])
            np.testing.assert_array_equal(
                dist_e[r], dist_o, err_msg=f"distances diverge at round {r}")
            assert m_e[r] == cluster.rmr.m, f"m diverges at round {r}"
            assert n_e[r] == cluster.rmr.n, f"n diverges at round {r}"
            n_prunes_o = sum(len(p) for p in cluster.prunes.values())
            assert prunes_e[r] == n_prunes_o, f"prunes diverge at round {r}"
            cluster.prune_connections(node_map, stakes_map)

        # prune application parity: engine per-slot bits == oracle filters
        active = np.asarray(state.active[0])
        pruned = np.asarray(state.pruned[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            peers = node.active_set.entries[bucket].peers
            for slot in range(params.active_set_size):
                j = active[i, slot]
                if j >= n or j == origin_idx:
                    # peer == origin: the oracle's self-seeded filter
                    # (push_active_set.rs:179) is the engine's implicit
                    # ``peer != origin`` mask, not a stored bit
                    continue
                assert (origin_pk in peers[index.pubkeys[j]]) == bool(
                    pruned[i, slot]), (i, slot)


class TestEdgeDetail:
    def test_edge_matrix_consistent_with_distances(self):
        """edge_detail exports the per-edge hop matrix (the engine's
        equivalent of the reference's orders dump, gossip.rs:374-390):
        every delivered edge (src -> tgt) carries hop dist[src] + 1."""
        _, tables, params, origins, state = _init(60, n_origins=1,
                                                  warm_up_rounds=0)
        state, rows = run_rounds(params, tables, origins, state, 3,
                                 detail=True, edge_detail=True)
        dist = np.asarray(rows["dist"])[:, 0]           # [r, N]
        tg = np.asarray(rows["push_targets"])[:, 0]     # [r, N, F]
        eh = np.asarray(rows["edge_hops"])[:, 0]
        for r in range(3):
            sent = tg[r] >= 0
            src_hop = np.broadcast_to(dist[r][:, None] + 1, sent.shape)
            np.testing.assert_array_equal(eh[r][sent], src_hop[sent])
            # delivered targets are reached at <= the edge's hop
            t_dist = dist[r][tg[r][sent]]
            assert (t_dist >= 0).all()
            assert (t_dist <= eh[r][sent]).all()


class TestOracleParityWideFanout(TestOracleParity):
    """push_fanout 18 exceeds the old hard inbound_cap=16; the auto-sized
    ranking width (params.k_inbound = max(16, 2*fanout) = 36) must keep
    received-cache scoring exact vs the oracle (received_cache.rs:83-98).
    Inherits the bit-exact parity assertions."""

    N = 40
    ROUNDS = 22
    PARAMS = {"push_fanout": 18, "active_set_size": 20, "inbound_cap": 0}


class TestLargeCluster:
    def test_20k_nodes_two_rounds(self):
        """N=20,000 crosses the old 16,384 packing ceiling: the widened
        pack base (engine/core.py _pack_base) must keep the round exact.
        Invariant-level check only (oracle would be too slow here)."""
        n = 20_000
        rng = np.random.default_rng(11)
        stakes = (np.exp(rng.normal(9.5, 2.0, n)).astype(np.int64) + 1) * 10**9
        tables = make_cluster_tables(stakes)
        params = EngineParams(num_nodes=n, warm_up_rounds=0)
        origins = jnp.arange(1, dtype=jnp.int32)
        state = init_state(jax.random.PRNGKey(0), tables, origins, params)
        active = np.asarray(state.active)
        assert ((active >= 0) & (active <= n)).all()
        state, rows = run_rounds(params, tables, origins, state, 2)
        cov = np.asarray(rows["coverage"])
        assert cov.shape == (2, 1) and (cov > 0.95).all(), cov
        # received-cache rows stay sorted/dup-free through the widened keys
        rc = np.asarray(state.rc_src)
        members = rc < n
        inner = members[..., 1:] & members[..., :-1]
        assert (np.diff(rc, axis=-1)[inner] > 0).all()

    def test_40k_tables_build(self):
        """N=40,000 crossed the old i32 sort-key cap (32767) and used to
        raise here; the i64 key path (engine/core.py) lifts the cap to
        MAX_NODES = 2^24, so table construction must now succeed."""
        from gossip_sim_tpu.engine.core import MAX_NODES, MAX_NODES_I32
        n = 40_000
        assert n > MAX_NODES_I32
        tables = make_cluster_tables(_synthetic(n, seed=3))
        assert int(tables.buckets.shape[0]) == n
        with pytest.raises(ValueError, match="num_nodes"):
            make_cluster_tables(np.ones(MAX_NODES + 1, np.int64))

    def test_i64_key_round_trip_40k(self):
        """The peer*pack+owner match keys at N=40,000: every (peer, owner)
        pair must survive the pack -> sort-arithmetic -> unpack round trip
        exactly in i64, and the widest key must genuinely overflow i32
        (i.e. the i64 path is load-bearing, not decorative)."""
        from gossip_sim_tpu.engine.core import _keys_need_i64, _pack_base
        n = 40_000
        assert _keys_need_i64(n) and not _keys_need_i64(1_000)
        pack = _pack_base(n)
        assert pack >= n and (pack & (pack - 1)) == 0
        rng = np.random.default_rng(0)
        peer = rng.integers(0, n, 4096).astype(np.int64)
        owner = rng.integers(0, n, 4096).astype(np.int64)
        # the engine's live/edge bit ride-along: key = (p*pack+o)*2 + 1
        keys = (peer * pack + owner) * 2 + 1
        assert keys.max() >= (1 << 31), "40k keys must exceed i32 range"
        assert keys.max() < (1 << 62), "keys stay below the BIG64 sentinel"
        np.testing.assert_array_equal((keys >> 1) // pack, peer)
        np.testing.assert_array_equal((keys >> 1) % pack, owner)

    @pytest.mark.slow
    def test_force_i64_keys_bit_parity(self):
        """FORCE_I64_KEYS drives a within-i32-bound cluster through the
        i64 sort-key arms; every engine row must stay bit-identical (the
        wider keys change cost, never the join semantics).  The flag is
        not part of the jit key, so the compile cache is cleared around
        the toggle — which forces every later engine test to recompile,
        hence slow-marked: the tier-1 guarantee is kept by the same
        check in tools/sparse_smoke.py (its own process, no knock-on)."""
        from gossip_sim_tpu.engine import clear_compile_cache
        from gossip_sim_tpu.engine import core as engine_core
        _, tables, params, origins, state0 = _init(
            200, n_origins=2, warm_up_rounds=0)
        _, ref = run_rounds(params, tables, origins, state0, 6)
        try:
            engine_core.FORCE_I64_KEYS = True
            clear_compile_cache()
            _, tables, params, origins, state0 = _init(
                200, n_origins=2, warm_up_rounds=0)
            _, wide = run_rounds(params, tables, origins, state0, 6)
        finally:
            engine_core.FORCE_I64_KEYS = False
            clear_compile_cache()
        for k in ref:
            np.testing.assert_array_equal(
                np.asarray(ref[k]), np.asarray(wide[k]), err_msg=k)


class TestSparseRepresentation:
    @pytest.mark.slow
    def test_sparse_bit_equal_to_dense(self):
        """representation='sparse' (engine/sparse.py frontier kernels, no
        rc stake planes) is a layout change, not a semantics change:
        every engine row bit-matches dense over multiple rounds, and the
        sparse state really carries the stake planes at zero width.
        Slow-marked (two fresh engine compiles on a tier-1 budget already
        at its ceiling): tools/sparse_smoke.py enforces the same parity
        every CI run, at 1k nodes under faults and against the pre-PR
        golden — strictly stronger than this unit check."""
        _, tables, params, origins, state = _init(
            300, n_origins=2, warm_up_rounds=0)
        _, ref = run_rounds(params, tables, origins, state, 6)

        sparams = params._replace(representation="sparse").validate()
        _, tables, _, origins, sstate = _init(
            300, n_origins=2, warm_up_rounds=0, representation="sparse")
        sstate, rows = run_rounds(sparams, tables, origins, sstate, 6)
        for k in ref:
            np.testing.assert_array_equal(
                np.asarray(ref[k]), np.asarray(rows[k]), err_msg=k)
        assert np.asarray(sstate.rc_shi).shape == (2, 300, 0)
        assert np.asarray(sstate.rc_slo).shape == (2, 300, 0)


class TestMultiChip:
    def test_sharded_equals_single_device(self):
        n_dev = len(jax.devices())
        assert n_dev == 8, "conftest forces an 8-device CPU topology"
        from gossip_sim_tpu.parallel import make_mesh, shard_sim

        stakes = _synthetic(64, seed=5)
        tables = make_cluster_tables(stakes)
        params = EngineParams(num_nodes=64, warm_up_rounds=0)
        origins = jnp.arange(8, dtype=jnp.int32)
        state = init_state(jax.random.PRNGKey(2), tables, origins, params)
        ref_state, ref_rows = run_rounds(params, tables, origins, state, 5)

        mesh = make_mesh(8, node_shards=2)
        state2 = init_state(jax.random.PRNGKey(2), tables, origins, params)
        state2, origins_s = shard_sim(mesh, state2, origins)
        sh_state, sh_rows = run_rounds(params, tables, origins_s, state2, 5)

        for k in ref_rows:
            np.testing.assert_array_equal(
                np.asarray(ref_rows[k]), np.asarray(sh_rows[k]),
                err_msg=f"row {k} diverges under sharding")
        np.testing.assert_array_equal(np.asarray(ref_state.active),
                                      np.asarray(sh_state.active))
        np.testing.assert_array_equal(np.asarray(ref_state.rc_src),
                                      np.asarray(sh_state.rc_src))
