"""Capacity observatory (obs/capacity.py + obs/memwatch.py, ISSUE 13).

The load-bearing contract is *exactness*: the closed-form ledger must
predict the live donated-buffer pytree bytes bit-for-bit, at more than
one (N, S, M) point, so its N-scaling extrapolations (capacity_report,
fit-budget) are arithmetic rather than estimates.
"""
import json
import os
import sys
import time

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.engine.lanes import (broadcast_state, run_rounds_lanes,
                                         stack_knobs)
from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                           init_traffic_state,
                                           run_traffic_rounds)
from gossip_sim_tpu.obs import capacity, memwatch
from gossip_sim_tpu.obs.report import build_run_report, validate_run_report
from gossip_sim_tpu.obs.spans import SpanRegistry


def synth_stakes(n, seed=3):
    rng = np.random.default_rng(seed)
    return (np.exp(rng.normal(9.5, 2.0, n)).astype(np.int64) + 1) * 10 ** 9


# --------------------------------------------------------------------------
# ledger exactness (the satellite contract: two (N, S, M) points + the
# closed-form extrapolation matching a second live instantiation)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("n,o,s", [(64, 1, 12), (150, 3, 8)])
def test_sim_state_ledger_exact(n, o, s):
    params = EngineParams(num_nodes=n, active_set_size=s)
    tables = make_cluster_tables(synth_stakes(n))
    origins = jnp.arange(o, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(0), tables, origins, params)
    live, _ = capacity.measure_pytree(state)
    assert capacity.predict_sim_state_bytes(params, o) == live


@pytest.mark.parametrize("n,o", [(64, 1), (150, 2)])
def test_request_ledger_exact(n, o):
    # the serve daemon's admission price (ISSUE 20): one request's lane
    # slice, priced without touching the device, equals the live bytes
    # of the state the daemon would actually splice in
    params = EngineParams(num_nodes=n)
    tables = make_cluster_tables(synth_stakes(n))
    origins = jnp.arange(o, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(2), tables, origins, params)
    live, _ = capacity.measure_pytree(state)
    assert capacity.predict_request_bytes(params, o) == live
    assert capacity.predict_request_bytes(params, origins) == live
    with pytest.raises(ValueError):
        capacity.predict_request_bytes(params, 0)


@pytest.mark.parametrize("mode", ["push", "push-pull", "adaptive"])
def test_sim_state_ledger_exact_across_modes(mode):
    # SimState geometry is mode-invariant (the pull accumulators always
    # exist); the ledger must agree under every gossip mode
    params = EngineParams(num_nodes=80, gossip_mode=mode)
    tables = make_cluster_tables(synth_stakes(80))
    origins = jnp.asarray([0], dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(1), tables, origins, params)
    state, _ = run_rounds(params, tables, origins, state, 2)
    live, _ = capacity.measure_pytree(state)
    assert capacity.predict_sim_state_bytes(params, 1) == live


@pytest.mark.parametrize("n,m", [(64, 4), (100, 9)])
def test_traffic_state_ledger_exact(n, m):
    params = EngineParams(num_nodes=n, traffic_values=m,
                          node_ingress_cap=8, node_egress_cap=8,
                          warm_up_rounds=0)
    stakes = synth_stakes(n)
    state = init_traffic_state(stakes, params, seed=0)
    state, _ = run_traffic_rounds(params, make_cluster_tables(stakes),
                                  device_traffic_tables(stakes), state, 2)
    live, _ = capacity.measure_pytree(state)
    assert capacity.predict_traffic_state_bytes(params) == live


def test_lane_state_ledger_exact():
    K = 3
    params = EngineParams(num_nodes=96)
    tables = make_cluster_tables(synth_stakes(96))
    origins = jnp.asarray([0], dtype=jnp.int32)
    base = init_state(jax.random.PRNGKey(0), tables, origins, params)
    knobs = stack_knobs([params._replace(
        probability_of_rotation=0.01 + 0.001 * k).knob_values()
        for k in range(K)])
    states, _ = run_rounds_lanes(params.static_part(), tables, origins,
                                 broadcast_state(base, K), knobs, 1)
    live, _ = capacity.measure_pytree(states)
    assert capacity.predict_sim_state_bytes(params, 1, lanes=K) == live


def test_extrapolation_matches_second_live_instantiation():
    # the SAME closed forms evaluated at a different N must equal a live
    # instantiation there — extrapolation is exact, not a fit
    params = EngineParams(num_nodes=64)
    n2 = 131
    p2 = params._replace(num_nodes=n2)
    tables2 = make_cluster_tables(synth_stakes(n2))
    origins = jnp.asarray([0], dtype=jnp.int32)
    state2 = init_state(jax.random.PRNGKey(0), tables2, origins, p2)
    live2, _ = capacity.measure_pytree(state2)
    assert capacity.predict_sim_state_bytes(p2, 1) == live2
    # and through the ledger_total_at path (state + tables + knobs)
    tables_live, _ = capacity.measure_pytree(tables2)
    knobs_live, _ = capacity.measure_pytree(p2.knob_values())
    assert capacity.ledger_total_at(params, n2) == (live2 + tables_live
                                                    + knobs_live)


def test_tables_and_knobs_exact():
    params = EngineParams(num_nodes=77)
    tables = make_cluster_tables(synth_stakes(77))
    live, _ = capacity.measure_pytree(tables)
    assert sum(e.bytes
               for e in capacity.cluster_tables_entries(params)) == live
    klive, _ = capacity.measure_pytree(params.knob_values())
    assert sum(e.bytes for e in capacity.knobs_entries()) == klive


def test_trace_block_rounds_matches_cli_harvest_block():
    from gossip_sim_tpu.cli import HARVEST_BLOCK
    assert capacity.TRACE_BLOCK_ROUNDS == HARVEST_BLOCK


# --------------------------------------------------------------------------
# ledger structure + planning queries
# --------------------------------------------------------------------------

def test_ledger_flags_dense_terms_only_under_all_origins():
    params = EngineParams(num_nodes=500)
    single = capacity.capacity_ledger(params, origin_batch=1)
    assert [e for e in single["entries"]
            if e["exact"] and e["n_degree"] >= 2] == []
    allo = capacity.capacity_ledger(params, origin_batch=500,
                                    origins_scale_with_n=True)
    dense = [e["name"] for e in allo["entries"]
             if e["exact"] and e["n_degree"] >= 2]
    assert "active" in dense and "rc_src" in dense
    assert allo["dense_terms"]
    assert allo["dense_bytes"] > 0


def test_ledger_is_json_safe_and_grouped():
    led = capacity.capacity_ledger(EngineParams(num_nodes=200),
                                   origin_batch=2, trace=True)
    json.dumps(led)
    assert led["schema"] == capacity.CAPACITY_SCHEMA
    for group in ("active-set", "received-cache", "stats", "tables",
                  "knobs", "trace"):
        assert led["groups"][group] > 0
    # exact group totals re-sum to the total
    assert sum(led["groups"].values()) == led["total_bytes"]
    assert led["bytes_per_node"] == pytest.approx(led["total_bytes"] / 200,
                                                  abs=0.01)


def test_fit_budget_is_tight():
    params = EngineParams(num_nodes=100)
    budget = capacity.parse_size("64MiB")
    n = capacity.fit_budget(params, budget)
    assert capacity.ledger_total_at(params, n) <= budget
    assert capacity.ledger_total_at(params, n + 1) > budget


def test_fit_budget_all_origins_is_quadratically_smaller():
    params = EngineParams(num_nodes=100)
    budget = capacity.parse_size("1GiB")
    n_single = capacity.fit_budget(params, budget)
    n_all = capacity.fit_budget(params, budget,
                                origins_scale_with_n=True)
    assert 0 < n_all < n_single


def test_parse_size():
    assert capacity.parse_size("16GB") == 16 * 2 ** 30
    assert capacity.parse_size("512MiB") == 512 * 2 ** 20
    assert capacity.parse_size("2e3") == 2000
    assert capacity.parse_size(1234) == 1234
    assert capacity.parse_size("1k") == 1000


# --------------------------------------------------------------------------
# XLA cost harvest
# --------------------------------------------------------------------------

def test_harvest_disabled_is_a_noop():
    capacity.reset_harvests()
    capacity.set_harvest_enabled(False)
    f = jax.jit(lambda x: x * 2)
    capacity.harvest_dispatch("test/site", f, (jnp.ones(4),))
    assert capacity.harvest_summary()["harvests"] == 0


def test_harvest_keyed_reuse_and_epoch():
    capacity.reset_harvests()
    capacity.set_harvest_enabled(True)
    try:
        f = jax.jit(lambda x: (x * 2).sum())
        args = (jnp.ones(8),)
        capacity.harvest_dispatch("test/site", f, args)
        capacity.harvest_dispatch("test/site", f, args)   # same key
        s = capacity.harvest_summary()
        assert s["harvests"] == 1 and s["reused"] == 1
        assert s["flops"] >= 0
        assert s["peak_argument_bytes"] == jnp.ones(8).nbytes
        # a different signature is a new compile-cache entry
        capacity.harvest_dispatch("test/site", f, (jnp.ones(16),))
        assert capacity.harvest_summary()["harvests"] == 2
        # a supervisor re-dispatch invalidates the keying (resilience.py)
        capacity.bump_dispatch_epoch()
        capacity.harvest_dispatch("test/site", f, args)
        s = capacity.harvest_summary()
        assert s["harvests"] == 3 and s["failures"] == 0
        assert capacity.site_peaks("test/site")["harvests"] == 3
    finally:
        capacity.set_harvest_enabled(False)
        capacity.reset_harvests()


def test_harvest_through_run_rounds_matches_live_bytes():
    # the engine hook harvests the real executable: its argument bytes
    # must cover the state the ledger predicts (state is one of the args)
    capacity.reset_harvests()
    capacity.set_harvest_enabled(True)
    try:
        params = EngineParams(num_nodes=64)
        tables = make_cluster_tables(synth_stakes(64))
        origins = jnp.asarray([0], dtype=jnp.int32)
        state = init_state(jax.random.PRNGKey(0), tables, origins, params)
        state, _ = run_rounds(params, tables, origins, state, 2)
        s = capacity.harvest_summary()
        assert s["harvests"] == 1 and s["failures"] == 0
        peaks = capacity.site_peaks("engine/run_rounds")
        assert peaks["argument_bytes"] >= capacity.predict_sim_state_bytes(
            params, 1)
    finally:
        capacity.set_harvest_enabled(False)
        capacity.reset_harvests()


# --------------------------------------------------------------------------
# memwatch
# --------------------------------------------------------------------------

def test_rss_and_peak_nonzero():
    assert memwatch.rss_bytes() > 0
    assert memwatch.peak_rss_bytes() >= memwatch.rss_bytes() // 2


def test_memwatch_samples_and_snapshot():
    mw = memwatch.MemWatch(0.01)
    mw.start()
    time.sleep(0.08)
    mw.stop()
    snap = mw.snapshot()
    assert snap["samples"] >= 3
    assert snap["peak_rss_bytes"] > 0
    assert snap["last_rss_bytes"] > 0
    assert snap["rss_series"] and len(snap["rss_series"][0]) == 2
    json.dumps(snap)


def test_memwatch_series_decimates_bounded():
    mw = memwatch.MemWatch(0.001, max_series=32)
    for _ in range(200):
        mw.sample_once()
    snap = mw.snapshot()
    assert snap["samples"] == 200
    assert len(snap["rss_series"]) < 32
    assert snap["series_stride"] > 1


def test_memwatch_module_reset_drops_previous_run():
    # one process == one run: a later run must never report an earlier
    # run's sampler series (cli main() resets alongside the registry)
    memwatch.start(0.01)
    time.sleep(0.03)
    memwatch.stop()
    assert memwatch.snapshot()["samples"] > 0
    memwatch.reset()
    snap = memwatch.snapshot()
    assert snap["samples"] == 0 and snap["enabled"] is False
    assert snap["peak_rss_bytes"] > 0   # kernel mark survives, honestly


def test_module_snapshot_without_start_carries_kernel_peak():
    snap = memwatch.snapshot()
    assert snap["peak_rss_bytes"] > 0
    json.dumps(snap)


# --------------------------------------------------------------------------
# report integration
# --------------------------------------------------------------------------

def test_run_report_capacity_section():
    reg = SpanRegistry()
    reg.set_info("platform", "cpu")
    reg.set_info("num_nodes", 40)
    led = capacity.capacity_ledger(EngineParams(num_nodes=40))
    reg.set_info("capacity_ledger", led)

    from gossip_sim_tpu.config import Config
    report = build_run_report(Config(gossip_iterations=4), reg)
    assert validate_run_report(report) == []
    cap = report["capacity"]
    assert cap["ledger"]["total_bytes"] == led["total_bytes"]
    assert cap["memwatch"]["peak_rss_bytes"] > 0
    assert "harvests" in cap["cost"]
    json.dumps(report)
