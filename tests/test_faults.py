"""Fault-injection subsystem tests (faults.py): hash-primitive parity
between the scalar (oracle) and vectorized (engine) paths, bipartition
determinism, reference-parity gating, and EXACT oracle-vs-engine parity
under packet loss + continuous churn + a healing partition.

The parity harness reuses the forced-active-set technique from
tests/test_engine.py: with rotation off and the oracle's active sets copied
from the engine's sampled ones, both backends are fully deterministic, so
the delivered set (distances), per-round failed masks, and the
delivered/dropped/suppressed counters must match bit-for-bit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.constants import UNREACHED
from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.faults import (SALT_CHURN, SALT_EDGE, FaultInjector,
                                   edge_u32, edge_u32_arr, fmix32, fmix32_arr,
                                   node_u32, node_u32_arr, partition_active,
                                   rate_threshold, round_basis,
                                   round_basis_arr, stake_bipartition)
from gossip_sim_tpu.identity import (NodeIndex, get_stake_bucket,
                                     pubkey_new_unique)
from gossip_sim_tpu.oracle.cluster import Cluster, Node


# --------------------------------------------------------------------------
# hash primitives: scalar path == numpy path == jax path, bit for bit
# --------------------------------------------------------------------------

class TestHashPrimitives:
    def test_fmix32_scalar_matches_arrays(self):
        xs = np.random.default_rng(0).integers(0, 1 << 32, 256,
                                               dtype=np.uint32)
        scalar = np.array([fmix32(int(x)) for x in xs], dtype=np.uint64)
        np.testing.assert_array_equal(
            scalar, fmix32_arr(xs, np).astype(np.uint64))
        np.testing.assert_array_equal(
            scalar, np.asarray(fmix32_arr(jnp.asarray(xs), jnp),
                               dtype=np.uint64))

    def test_edge_and_node_hashes_match_vectorized(self):
        basis = round_basis(42, 7, SALT_EDGE)
        src = np.arange(64, dtype=np.uint32)
        dst = np.arange(64, dtype=np.uint32)[::-1].copy()
        scalar = np.array([edge_u32(basis, int(s), int(d))
                           for s, d in zip(src, dst)], dtype=np.uint64)
        np.testing.assert_array_equal(
            scalar,
            edge_u32_arr(np.uint32(basis), src, dst, np).astype(np.uint64))
        np.testing.assert_array_equal(
            scalar,
            np.asarray(edge_u32_arr(jnp.uint32(basis), jnp.asarray(src),
                                    jnp.asarray(dst), jnp),
                       dtype=np.uint64))

        basis_c = round_basis(42, 7, SALT_CHURN)
        scalar_n = np.array([node_u32(basis_c, int(i)) for i in src],
                            dtype=np.uint64)
        np.testing.assert_array_equal(
            scalar_n,
            node_u32_arr(np.uint32(basis_c), src, np).astype(np.uint64))

    def test_round_basis_traced_iteration_matches_scalar(self):
        """The engine hands a traced int32 iteration into round_basis_arr;
        the result must equal the oracle's pure-int basis."""
        def f(it):
            return round_basis_arr(9, it, SALT_EDGE, jnp)
        for it in (0, 1, 17, 4095):
            assert int(jax.jit(f)(jnp.int32(it))) == round_basis(
                9, it, SALT_EDGE)

    def test_rate_threshold_endpoints(self):
        assert rate_threshold(0.0) == 0
        assert rate_threshold(-1.0) == 0
        assert rate_threshold(1.0) == 1 << 32
        assert rate_threshold(2.0) == 1 << 32
        # strictly monotone interior and always hit/miss at the endpoints
        assert 0 < rate_threshold(0.25) < rate_threshold(0.75) < (1 << 32)
        assert (1 << 32) - 1 < rate_threshold(1.0)  # max u32 still fires

    def test_partition_active_window(self):
        assert not partition_active(5, -1, -1)
        assert partition_active(5, 5, -1)
        assert not partition_active(4, 5, -1)
        assert partition_active(7, 5, 8)
        assert not partition_active(8, 5, 8)

    def test_stake_bipartition_balanced_and_deterministic(self):
        rng = np.random.default_rng(3)
        stakes = rng.integers(1, 1 << 40, 501, dtype=np.int64)
        side = stake_bipartition(stakes)
        side2 = stake_bipartition(stakes)
        np.testing.assert_array_equal(side, side2)
        s0 = int(stakes[~side].sum())
        s1 = int(stakes[side].sum())
        # greedy balance: the gap never exceeds the largest single stake
        assert abs(s0 - s1) <= int(stakes.max())
        assert 0 < side.sum() < len(stakes)


# --------------------------------------------------------------------------
# reference-parity gating: all-off knobs compile the identical round
# --------------------------------------------------------------------------

def test_default_params_have_no_impairments():
    p = EngineParams(num_nodes=16)
    assert not p.has_impairments
    assert not p.has_churn


def test_engine_unimpaired_rows_identical_with_zero_knobs():
    """Explicit zero knobs and the defaults select the same compiled round:
    every row (including the new counters) must match bit-for-bit, and the
    impairment counters stay zero."""
    rng = np.random.default_rng(5)
    stakes = rng.choice(np.arange(1, 5000), 80, replace=False).astype(
        np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    origins = jnp.arange(2, dtype=jnp.int32)
    base = EngineParams(num_nodes=80, warm_up_rounds=0)
    explicit = base._replace(packet_loss_rate=0.0, churn_fail_rate=0.0,
                             churn_recover_rate=0.0, partition_at=-1,
                             heal_at=-1, impair_seed=123)
    out = {}
    for name, params in (("default", base), ("explicit", explicit)):
        state = init_state(jax.random.PRNGKey(2), tables, origins, params)
        _, rows = run_rounds(params, tables, origins, state, 8)
        out[name] = jax.tree_util.tree_map(np.asarray, rows)
    assert set(out["default"]) == set(out["explicit"])
    for k in out["default"]:
        np.testing.assert_array_equal(out["default"][k], out["explicit"][k],
                                      err_msg=k)
    assert (out["default"]["dropped"] == 0).all()
    assert (out["default"]["suppressed"] == 0).all()
    np.testing.assert_array_equal(out["default"]["delivered"],
                                  out["default"]["m"])


def test_params_validation():
    with pytest.raises(AssertionError, match="impairment rates"):
        EngineParams(num_nodes=16, packet_loss_rate=1.5).validate()
    with pytest.raises(AssertionError, match="heal_at"):
        EngineParams(num_nodes=16, partition_at=10, heal_at=5).validate()


# --------------------------------------------------------------------------
# oracle-vs-engine bit-exact parity under loss + churn + partition
# --------------------------------------------------------------------------

class TestFaultParity:
    """>= 1k nodes, shared seeds, forced-identical active sets, rotation
    off: delivered set, hop counts, failed masks, and the degraded-delivery
    counters must match bit-for-bit every round."""

    N = 1024
    ROUNDS = 8
    SEED = 99
    KNOBS = dict(packet_loss_rate=0.15, churn_fail_rate=0.02,
                 churn_recover_rate=0.25, partition_at=2, heal_at=5)

    @pytest.fixture()
    def pair(self):
        n = self.N
        rng = np.random.default_rng(17)
        stakes_arr = rng.choice(np.arange(1, 50 * n), size=n,
                                replace=False).astype(np.int64) * 10**9
        accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)

        tables = make_cluster_tables(stakes_np)
        params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                              warm_up_rounds=0, impair_seed=self.SEED,
                              **self.KNOBS).validate()
        origin_idx = 0
        origins = jnp.asarray([origin_idx], jnp.int32)
        state = init_state(jax.random.PRNGKey(11), tables, origins, params)

        stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
        nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
        origin_pk = index.pubkeys[origin_idx]
        active = np.asarray(state.active[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            entry = node.active_set.entries[bucket]
            entry.peers = {index.pubkeys[j]: {index.pubkeys[j]}
                           for j in active[i] if j < n}
        return (index, stakes_map, nodes, origin_pk,
                tables, params, origins, state)

    def test_exact_parity_under_faults(self, pair):
        (index, stakes_map, nodes, origin_pk,
         tables, params, origins, state) = pair
        n = self.N
        node_map = {nd.pubkey: nd for nd in nodes}
        cluster = Cluster(params.push_fanout)
        impair = FaultInjector(index, seed=self.SEED, **self.KNOBS)
        assert impair.has_churn

        state, rows = run_rounds(params, tables, origins, state,
                                 self.ROUNDS, detail=True)
        dist_e = np.asarray(rows["dist"])[:, 0]          # [rounds, N]
        failed_e = np.asarray(rows["failed_mask"])[:, 0]  # [rounds, N]
        m_e = np.asarray(rows["m"])[:, 0]
        n_e = np.asarray(rows["n"])[:, 0]
        delivered_e = np.asarray(rows["delivered"])[:, 0]
        dropped_e = np.asarray(rows["dropped"])[:, 0]
        suppressed_e = np.asarray(rows["suppressed"])[:, 0]
        failed_cnt_e = np.asarray(rows["failed_count"])[:, 0]

        saw_drop = saw_sup = saw_churn = False
        for r in range(self.ROUNDS):
            impair.begin_round(r)
            newly_failed, newly_recovered = impair.churn_step(
                r, node_map, cluster.failed_nodes)
            saw_churn |= bool(newly_failed or newly_recovered)
            cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
            cluster.consume_messages(origin_pk, nodes)
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)

            failed_o = np.array([node_map[pk].failed
                                 for pk in index.pubkeys])
            np.testing.assert_array_equal(
                failed_e[r], failed_o,
                err_msg=f"failed mask diverges at round {r}")
            assert failed_cnt_e[r] == failed_o.sum()

            dist_o = np.array(
                [-1 if cluster.distances[pk] == UNREACHED
                 else cluster.distances[pk] for pk in index.pubkeys])
            np.testing.assert_array_equal(
                dist_e[r], dist_o,
                err_msg=f"distances diverge at round {r}")
            assert m_e[r] == cluster.rmr.m, f"m diverges at round {r}"
            assert n_e[r] == cluster.rmr.n, f"n diverges at round {r}"
            assert delivered_e[r] == impair.delivered, f"round {r}"
            assert dropped_e[r] == impair.dropped, f"round {r}"
            assert suppressed_e[r] == impair.suppressed, f"round {r}"
            saw_drop |= impair.dropped > 0
            saw_sup |= impair.suppressed > 0
            # partition window: suppression only inside [partition_at, heal_at)
            if not (self.KNOBS["partition_at"] <= r < self.KNOBS["heal_at"]):
                assert suppressed_e[r] == 0
            cluster.prune_connections(node_map, stakes_map)

        # the regime actually exercised every fault class
        assert saw_drop and saw_sup and saw_churn


class TestFaultParityDynamicKnobs(TestFaultParity):
    """ISSUE 4: the same 1k-node oracle-vs-engine bit-exact check, but with
    the engine's executable compiled for DIFFERENT knob values first — the
    parity run is then a pure jit-cache hit with its knob values flowing in
    as traced scalars, proving the dynamic-knob engine (not a per-value
    recompile) matches the oracle bit-for-bit."""

    N = 1024
    ROUNDS = 6
    SEED = 31
    KNOBS = dict(packet_loss_rate=0.2, churn_fail_rate=0.03,
                 churn_recover_rate=0.3, partition_at=1, heal_at=4)

    def test_exact_parity_under_faults(self, pair):
        from gossip_sim_tpu.engine import compiled_cache_size

        (index, stakes_map, nodes, origin_pk,
         tables, params, origins, state) = pair
        # compile carrier: same static key, every numeric knob perturbed
        warm = params._replace(packet_loss_rate=0.55, churn_fail_rate=0.2,
                               churn_recover_rate=0.05, partition_at=2,
                               heal_at=5, impair_seed=self.SEED + 7,
                               prune_stake_threshold=0.4)
        wstate = init_state(jax.random.PRNGKey(1), tables, origins, warm)
        run_rounds(warm, tables, origins, wstate, self.ROUNDS, detail=True)
        before = compiled_cache_size()
        super().test_exact_parity_under_faults(pair)
        if before >= 0:
            assert compiled_cache_size() == before, (
                "parity run recompiled instead of reusing the warm "
                "executable with swapped knob values")


class TestFaultParityLossOnly(TestFaultParity):
    """Loss without churn/partition takes the cheaper compiled path
    (no tfail rebuild, no side gather); parity must still hold."""

    N = 1024
    ROUNDS = 6
    SEED = 7
    KNOBS = dict(packet_loss_rate=0.3, churn_fail_rate=0.0,
                 churn_recover_rate=0.0, partition_at=-1, heal_at=-1)

    def test_exact_parity_under_faults(self, pair):
        (index, stakes_map, nodes, origin_pk,
         tables, params, origins, state) = pair
        node_map = {nd.pubkey: nd for nd in nodes}
        cluster = Cluster(params.push_fanout)
        impair = FaultInjector(index, seed=self.SEED, **self.KNOBS)
        assert not impair.has_churn

        state, rows = run_rounds(params, tables, origins, state,
                                 self.ROUNDS, detail=True)
        dist_e = np.asarray(rows["dist"])[:, 0]
        dropped_e = np.asarray(rows["dropped"])[:, 0]
        suppressed_e = np.asarray(rows["suppressed"])[:, 0]
        for r in range(self.ROUNDS):
            impair.begin_round(r)
            cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
            cluster.consume_messages(origin_pk, nodes)
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)
            dist_o = np.array(
                [-1 if cluster.distances[pk] == UNREACHED
                 else cluster.distances[pk] for pk in index.pubkeys])
            np.testing.assert_array_equal(
                dist_e[r], dist_o,
                err_msg=f"distances diverge at round {r}")
            assert dropped_e[r] == impair.dropped, f"round {r}"
            assert suppressed_e[r] == 0 and impair.suppressed == 0
            cluster.prune_connections(node_map, stakes_map)
        assert dropped_e.sum() > 0


# --------------------------------------------------------------------------
# engine-level fault behavior
# --------------------------------------------------------------------------

def _engine(n=256, seed=2, rounds=20, **kw):
    rng = np.random.default_rng(seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=n, warm_up_rounds=0, **kw).validate()
    origins = jnp.arange(1, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(seed), tables, origins, params)
    state, rows = run_rounds(params, tables, origins, state, rounds)
    return params, state, jax.tree_util.tree_map(np.asarray, rows)


def test_partition_heals_and_coverage_recovers():
    _, _, rows = _engine(partition_at=2, heal_at=10, rounds=16)
    cov = rows["coverage"][:, 0]
    sup = rows["suppressed"][:, 0]
    assert (sup[2:10] > 0).all(), "partition suppresses cross-edges"
    assert sup[:2].sum() == 0 and sup[10:].sum() == 0
    # a bipartition caps delivery near the origin's side; post-heal coverage
    # must recover to the unimpaired level
    assert cov[2:10].max() < 0.9
    assert cov[-1] > 0.99


def test_churn_reaches_fail_recover_equilibrium():
    p, state, rows = _engine(churn_fail_rate=0.1, churn_recover_rate=0.3,
                             rounds=60)
    failed = rows["failed_count"][:, 0]
    assert failed[0] > 0 or failed[1] > 0
    # stationary failed fraction ~ p_f / (p_f + p_r) = 0.25
    tail = failed[20:].mean() / p.num_nodes
    assert 0.1 < tail < 0.4
    # recovered nodes rejoin: the failed set actually shrinks sometimes
    assert (np.diff(failed.astype(int)) < 0).any()


def test_packet_loss_scales_with_rate():
    drops = {}
    for rate in (0.1, 0.5):
        _, _, rows = _engine(packet_loss_rate=rate, rounds=12, seed=4)
        d = rows["dropped"][:, 0].sum()
        t = d + rows["delivered"][:, 0].sum()
        drops[rate] = d / t
    assert drops[0.1] == pytest.approx(0.1, abs=0.04)
    assert drops[0.5] == pytest.approx(0.5, abs=0.06)


def test_hop_clamp_counter_counts_top_bin():
    """hist_bins=4 forces hop distances >= 3 into the clamp guard."""
    _, _, rows = _engine(n=256, hist_bins=4, rounds=3)
    clamped = rows["hop_clamped"][:, 0]
    cov = rows["coverage"][:, 0]
    # a 256-node fanout-6 BFS needs > 3 hops: the guard must fire
    assert cov[-1] > 0.9
    assert clamped.sum() > 0


def test_oracle_rmr_handles_total_delivery_collapse():
    """Heavy impairment can leave only the origin holding the message
    (n == 1); the oracle must report rmr = 0.0 like the engine instead of
    dividing by zero.  n == 0 (run_gossip never ran) still raises."""
    from gossip_sim_tpu.oracle.rmr import RelativeMessageRedundancy

    r = RelativeMessageRedundancy()
    r.increment_n()
    r.increment_m_by(3)   # prune messages can exist even with no delivery
    assert r.calculate() == (0.0, 3, 1)
    with pytest.raises(ZeroDivisionError):
        RelativeMessageRedundancy().calculate()
