"""Pull-gossip (anti-entropy) subsystem tests (pull.py; ISSUE 5).

Four contracts:

* **Mode gating** — ``gossip_mode="push"`` emits bit-identical rows/state
  to the engine's defaults (the pull block must not exist in the graph),
  and pull modes emit the pull rows with sane invariants.
* **Determinism** — the stateless counter-hash streams (peer draws, bloom
  FP, request loss) are reproducible and seed-separated; the shared
  class-CDF tables match the engine's sampler bit-for-bit.
* **Compile-once** — stepping every pull knob (fanout within the static
  slot width, interval, bloom FP rate, request cap) reuses one compiled
  executable; crossing the mode boundary recompiles.
* **1k-node oracle parity** — under push-pull with packet loss AND churn
  active, the sort-routed engine and the loop-based PullOracle +
  oracle Cluster agree bit-for-bit on coverage, combined hops, stranded
  sets, pull counters and per-node pull message deltas.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.constants import UNREACHED
from gossip_sim_tpu.engine import (EngineParams, clear_compile_cache,
                                   compiled_cache_size, init_state,
                                   make_cluster_tables, run_rounds)
from gossip_sim_tpu.identity import (NodeIndex, get_stake_bucket,
                                     pubkey_new_unique)
from gossip_sim_tpu.oracle.cluster import Cluster, Node
from gossip_sim_tpu.pull import (PULL_RESPONSE, PullOracle,
                                 pull_class_tables, sample_pull_peer)
from gossip_sim_tpu.faults import round_basis
from gossip_sim_tpu.pull import SALT_PULL_CLASS, SALT_PULL_MEMBER


def _stakes(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.choice(np.arange(1, 50 * n), size=n,
                      replace=False).astype(np.int64) * 10**9


def _run_engine(params, n, seed=3, rounds=6, **kw):
    tables = make_cluster_tables(_stakes(n, seed))
    origins = jnp.arange(1, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(seed), tables, origins, params)
    state, rows = run_rounds(params, tables, origins, state, rounds, **kw)
    return state, jax.tree_util.tree_map(np.asarray, rows)


# --------------------------------------------------------------------------
# mode gating
# --------------------------------------------------------------------------

class TestModeGating:
    N = 128

    def test_push_mode_bit_identical_to_defaults(self):
        """Explicit mode=push with pull knobs set emits the identical rows
        and state as the bare defaults — the pull block is gated out of the
        compiled graph, knob values notwithstanding."""
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0)
        explicit = base._replace(gossip_mode="push", pull_fanout=5,
                                 pull_interval=3, pull_bloom_fp_rate=0.4,
                                 pull_request_cap=2)
        s1, r1 = _run_engine(base, self.N, rounds=5, detail=True)
        s2, r2 = _run_engine(explicit, self.N, rounds=5, detail=True)
        assert set(r1) == set(r2)
        assert "pull_requests" not in r1 and "pull_hop" not in r1
        for k in r1:
            np.testing.assert_array_equal(r1[k], r2[k], err_msg=k)
        for f in s1._fields:
            np.testing.assert_array_equal(np.asarray(getattr(s1, f)),
                                          np.asarray(getattr(s2, f)),
                                          err_msg=f)
        assert (np.asarray(s1.pull_rescued_acc) == 0).all()

    @pytest.mark.slow  # tier-1 budget; tools/pull_smoke gate covers this
    def test_push_pull_leaves_push_phase_untouched(self):
        """The pull phase runs AFTER the push BFS and feeds nothing back
        into active sets / received caches, so the push rows (dist, m, n,
        rmr, prunes) are bit-identical with pull on or off."""
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            packet_loss_rate=0.3, impair_seed=4)
        pp = base._replace(gossip_mode="push-pull", pull_fanout=4)
        _, r_push = _run_engine(base, self.N, rounds=6, detail=True)
        s_pp, r_pp = _run_engine(pp, self.N, rounds=6, detail=True)
        for k in ("dist", "m", "n", "rmr", "prunes_sent", "delivered",
                  "dropped", "branching"):
            np.testing.assert_array_equal(r_push[k], r_pp[k], err_msg=k)
        # pull adds coverage on top of push (rescues are push-unreached)
        assert (r_pp["coverage"] >= r_push["coverage"]).all()
        resc = r_pp["pull_rescued"]
        np.testing.assert_array_equal(
            np.round((r_pp["coverage"] - r_push["coverage"]) * self.N)
            .astype(int), resc)
        # accounting identity: every arrived request responds or misses
        np.testing.assert_array_equal(
            r_pp["pull_requests"],
            r_pp["pull_responses"] + r_pp["pull_misses"])
        assert r_pp["pull_requests"].sum() > 0

    @pytest.mark.slow  # tier-1 budget; tools/pull_smoke gate covers this
    def test_pull_only_mode_pushes_nothing(self):
        p = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                         gossip_mode="pull", pull_fanout=4)
        _, rows = _run_engine(p, self.N, rounds=4, detail=True)
        assert (rows["m"] == 0).all() and (rows["delivered"] == 0).all()
        assert (rows["n"] == 1).all()          # only the origin holds
        # direct pulls from the origin are the only delivery path
        assert (rows["pull_hop"] <= 1).all()
        assert (rows["coverage"] * self.N
                == 1 + rows["pull_rescued"]).all()

    @pytest.mark.slow  # tier-1 budget; tools/pull_smoke gate covers this
    def test_pull_interval_gates_rounds(self):
        p = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                         gossip_mode="push-pull", pull_interval=3)
        _, rows = _run_engine(p, self.N, rounds=7)
        req = rows["pull_requests"][:, 0]
        assert (req[[0, 3, 6]] > 0).all()
        assert (req[[1, 2, 4, 5]] == 0).all()

    def test_request_cap_bounds_served_requests(self):
        """With cap=1, responses per peer per round are bounded by 1 —
        total responses <= N (and the capped misses show up)."""
        p = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                        gossip_mode="pull", pull_fanout=6,
                        pull_request_cap=1, pull_bloom_fp_rate=0.0)
        _, rows = _run_engine(p, self.N, rounds=3)
        assert (rows["pull_responses"] <= self.N).all()


# --------------------------------------------------------------------------
# determinism + shared tables
# --------------------------------------------------------------------------

class TestDeterminism:
    def test_class_tables_match_engine_sampler(self):
        """pull_class_tables' f32 CDF must equal the engine sampler's
        top-entry row bit-for-bit (the parity precondition)."""
        stakes = _stakes(500, seed=2)
        tables = make_cluster_tables(stakes)
        pt = pull_class_tables(stakes)
        np.testing.assert_array_equal(
            pt.cdf, np.asarray(tables.sampler.class_cdf[-1]))
        np.testing.assert_array_equal(
            pt.perm, np.asarray(tables.sampler.perm))
        np.testing.assert_array_equal(
            pt.class_start, np.asarray(tables.sampler.class_start))

    def test_bloom_fp_deterministic_and_seed_separated(self):
        """The same (seed, round) produces the identical pull outcome; a
        different impair seed produces a different draw stream."""
        stakes = _stakes(300, seed=5)
        hops = np.full(300, -1, np.int64)
        hops[0] = 0
        hops[1:40] = 1
        failed = np.zeros(300, bool)
        a = PullOracle(stakes, seed=7, pull_fanout=3, pull_bloom_fp_rate=0.5)
        b = PullOracle(stakes, seed=7, pull_fanout=3, pull_bloom_fp_rate=0.5)
        c = PullOracle(stakes, seed=8, pull_fanout=3, pull_bloom_fp_rate=0.5)
        ra, rb, rc = (x.run_round(2, hops, failed) for x in (a, b, c))
        np.testing.assert_array_equal(ra.peers, rb.peers)
        np.testing.assert_array_equal(ra.code, rb.code)
        assert ra.responses == rb.responses and ra.rescued == rb.rescued
        assert not np.array_equal(ra.peers, rc.peers)
        # with FP rate 0.5 and many misses both FP and non-FP cases occur
        assert ra.responses > 0 and ra.misses > 0

    def test_bloom_fp_rate_endpoints(self):
        """fp=1.0 kills every rescue; fp=0.0 never filters one."""
        stakes = _stakes(200, seed=1)
        hops = np.full(200, -1, np.int64)
        hops[:50] = np.arange(50) % 3
        failed = np.zeros(200, bool)
        never = PullOracle(stakes, seed=3, pull_fanout=4,
                           pull_bloom_fp_rate=1.0).run_round(0, hops, failed)
        assert never.responses == 0 and not never.rescued
        free = PullOracle(stakes, seed=3, pull_fanout=4,
                          pull_bloom_fp_rate=0.0).run_round(0, hops, failed)
        assert free.responses > 0
        assert (free.code == PULL_RESPONSE).sum() == free.responses

    def test_scalar_peer_draw_matches_class_distribution(self):
        """Empirical stake-class frequencies of the hash-driven draws match
        the (bucket+1)^2 class CDF (the weighted-shuffle machinery's
        weight profile at its top entry)."""
        from gossip_sim_tpu.identity import stake_buckets_array

        n = 400
        stakes = np.sort(_stakes(n, seed=9))[::-1].copy()  # desc by index
        buckets = stake_buckets_array(stakes.astype(np.uint64))
        pt = pull_class_tables(stakes)
        b_cls = round_basis(1, 0, SALT_PULL_CLASS)
        b_mem = round_basis(1, 0, SALT_PULL_MEMBER)
        draws = np.array([sample_pull_peer(pt, b_cls, b_mem, node, s)
                          for node in range(n) for s in range(16)])
        emp = np.bincount(buckets[draws], minlength=pt.cdf.size)
        emp = emp / emp.sum()
        expected = np.diff(np.concatenate([[0.0], pt.cdf.astype(np.float64)]))
        assert np.abs(emp - expected).max() < 0.03


# --------------------------------------------------------------------------
# compile-once (the PULL_FANOUT sweep invariant)
# --------------------------------------------------------------------------

class TestPullCompileOnce:
    N = 96

    @pytest.mark.slow  # tier-1 budget; tools/sweep_smoke + pull_smoke gate covers this
    def test_pull_knob_sweep_compiles_exactly_once(self):
        """A 3-step PULL_FANOUT sweep (plus interval/fp/cap steps) within
        the static pull_slots width builds ONE executable (the acceptance
        criterion)."""
        tables = make_cluster_tables(_stakes(self.N, seed=11))
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            gossip_mode="push-pull", pull_fanout=2)
        clear_compile_cache()
        before = compiled_cache_size()
        for k in range(3):
            p = base._replace(pull_fanout=2 + k,
                              pull_interval=1 + k,
                              pull_bloom_fp_rate=0.1 * (k + 1),
                              pull_request_cap=k)
            state = init_state(jax.random.PRNGKey(1), tables, origins, p)
            run_rounds(p, tables, origins, state, 3)
        assert compiled_cache_size() - before == 1, (
            "pull knob sweep recompiled")

    def test_mode_and_slot_changes_recompile(self):
        tables = make_cluster_tables(_stakes(self.N, seed=11))
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            gossip_mode="push-pull")
        state = init_state(jax.random.PRNGKey(1), tables, origins, base)
        run_rounds(base, tables, origins, state, 2)
        before = compiled_cache_size()
        # static slot width changes the array shapes -> one new executable
        wide = base._replace(pull_slots=16)
        state = init_state(jax.random.PRNGKey(1), tables, origins, wide)
        run_rounds(wide, tables, origins, state, 2)
        assert compiled_cache_size() == before + 1
        # crossing the mode boundary flips the phase selection
        push = base._replace(gossip_mode="push")
        state = init_state(jax.random.PRNGKey(1), tables, origins, push)
        run_rounds(push, tables, origins, state, 2)
        assert compiled_cache_size() == before + 2

    def test_fanout_beyond_slots_rejected(self):
        """Explicit pull_slots narrower than the fanout is a hard error;
        the auto rule (max(8, fanout)) always covers the fanout."""
        with pytest.raises(AssertionError, match="pull_slots"):
            EngineParams(num_nodes=16, gossip_mode="push-pull",
                         pull_fanout=9, pull_slots=4).validate()
        assert EngineParams(
            num_nodes=16, gossip_mode="push-pull",
            pull_fanout=9).validate().pull_slots_resolved == 9
        EngineParams(num_nodes=16, gossip_mode="push-pull", pull_fanout=9,
                     pull_slots=12).validate()


# --------------------------------------------------------------------------
# 1k-node oracle-vs-engine bit-exact parity under push-pull + faults
# --------------------------------------------------------------------------

class TestPullParity:
    """The acceptance gate: >= 1k nodes, shared seeds, forced-identical
    active sets, rotation off, packet loss AND churn active, push-pull
    mode — coverage, combined hops, stranded sets, pull counters and the
    per-node pull message deltas must match bit-for-bit every round."""

    N = 1024
    ROUNDS = 6
    SEED = 77
    KNOBS = dict(packet_loss_rate=0.15, churn_fail_rate=0.02,
                 churn_recover_rate=0.25)
    PULL = dict(pull_fanout=3, pull_interval=2, pull_bloom_fp_rate=0.25,
                pull_request_cap=3)

    def test_exact_parity_push_pull_under_faults(self):
        n = self.N
        rng = np.random.default_rng(23)
        stakes_arr = rng.choice(np.arange(1, 50 * n), size=n,
                                replace=False).astype(np.int64) * 10**9
        accounts = {pubkey_new_unique(): int(s) for s in stakes_arr}
        index = NodeIndex.from_stakes(accounts)
        stakes_np = index.stakes.astype(np.int64)

        tables = make_cluster_tables(stakes_np)
        params = EngineParams(num_nodes=n, probability_of_rotation=0.0,
                              warm_up_rounds=0, impair_seed=self.SEED,
                              gossip_mode="push-pull", **self.KNOBS,
                              **self.PULL).validate()
        origins = jnp.asarray([0], jnp.int32)
        state = init_state(jax.random.PRNGKey(13), tables, origins, params)

        stakes_map = {pk: int(s) for pk, s in zip(index.pubkeys, stakes_np)}
        nodes = [Node(pk, stakes_map[pk]) for pk in index.pubkeys]
        origin_pk = index.pubkeys[0]
        active = np.asarray(state.active[0])
        for i, node in enumerate(nodes):
            bucket = get_stake_bucket(min(stakes_map[node.pubkey],
                                          stakes_map[origin_pk]))
            entry = node.active_set.entries[bucket]
            entry.peers = {index.pubkeys[j]: {index.pubkeys[j]}
                           for j in active[i] if j < n}
        node_map = {nd.pubkey: nd for nd in nodes}

        from gossip_sim_tpu.faults import FaultInjector
        cluster = Cluster(params.push_fanout)
        impair = FaultInjector(index, seed=self.SEED, **self.KNOBS)
        pull_oracle = PullOracle(
            stakes_np, seed=self.SEED,
            pull_slots=params.pull_slots_resolved,
            packet_loss_rate=self.KNOBS["packet_loss_rate"], **self.PULL)

        state, rows = run_rounds(params, tables, origins, state,
                                 self.ROUNDS, detail=True)
        rows = jax.tree_util.tree_map(np.asarray, rows)

        saw_rescue = saw_pull_drop = False
        for r in range(self.ROUNDS):
            impair.begin_round(r)
            impair.churn_step(r, node_map, cluster.failed_nodes)
            cluster.run_gossip(origin_pk, stakes_map, node_map, impair)
            cluster.run_pull(pull_oracle, r, index, node_map)
            cluster.consume_messages(origin_pk, nodes)
            cluster.send_prunes(origin_pk, nodes,
                                params.prune_stake_threshold,
                                params.min_ingress_nodes, stakes_map)

            # push phase unchanged by pull (dist is the push view)
            dist_o = np.array(
                [-1 if cluster.distances[pk] == UNREACHED
                 else cluster.distances[pk] for pk in index.pubkeys])
            np.testing.assert_array_equal(
                rows["dist"][r, 0], dist_o,
                err_msg=f"push distances diverge at round {r}")

            pr = cluster.pull
            assert rows["pull_requests"][r, 0] == pr.requests, f"round {r}"
            assert rows["pull_responses"][r, 0] == pr.responses, f"round {r}"
            assert rows["pull_misses"][r, 0] == pr.misses, f"round {r}"
            assert rows["pull_dropped"][r, 0] == pr.dropped, f"round {r}"
            assert rows["pull_suppressed"][r, 0] == pr.suppressed
            assert rows["pull_rescued"][r, 0] == len(pr.rescued), f"round {r}"
            np.testing.assert_array_equal(
                rows["pull_hop"][r, 0], pr.pull_hop.astype(np.int32),
                err_msg=f"pull hops diverge at round {r}")

            # combined coverage + stranded set (stats-layer surface)
            cov_o, unvisited_o = cluster.coverage(stakes_map)
            assert int(rows["unvisited"][r, 0]) == unvisited_o, f"round {r}"
            stranded_o = {index.index_of(pk)
                          for pk in cluster.stranded_nodes()}
            stranded_e = set(np.nonzero(rows["stranded_mask"][r, 0])[0]
                             .tolist())
            assert stranded_e == stranded_o, f"round {r}"
            saw_rescue |= len(pr.rescued) > 0
            saw_pull_drop |= pr.dropped > 0
            cluster.prune_connections(node_map, stakes_map)

        # final per-node message counters: engine accumulators vs the
        # oracle's per-round counts are compared at the stats layer by
        # test_cli; here assert the pull deltas summed over rounds
        assert saw_rescue, "regime never exercised a pull rescue"
        assert saw_pull_drop, "regime never dropped a pull request"


def test_pull_message_counts_flow_into_engine_accumulators():
    """egress/ingress accumulators include the pull request/response
    messages: with pull on, totals strictly exceed the push-only run."""
    n = 128
    base = EngineParams(num_nodes=n, warm_up_rounds=0)
    s_push, _ = _run_engine(base, n, rounds=5)
    s_pp, rows = _run_engine(base._replace(gossip_mode="push-pull",
                                           pull_fanout=4), n, rounds=5)
    eg_push = int(np.asarray(s_push.egress_acc).sum())
    eg_pp = int(np.asarray(s_pp.egress_acc).sum())
    ing_pp = int(np.asarray(s_pp.ingress_acc).sum())
    req = int(rows["pull_requests"].sum())
    resp = int(rows["pull_responses"].sum())
    # push phase is identical, so the delta is exactly the pull messages
    assert eg_pp - eg_push == req + resp
    ing_push = int(np.asarray(s_push.ingress_acc).sum())
    assert ing_pp - ing_push == req + resp
    # the pull-tagged hop histogram counts exactly the rescues
    assert (np.asarray(s_pp.pull_hops_hist_acc).sum()
            == np.asarray(s_pp.pull_rescued_acc).sum())
