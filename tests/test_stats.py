"""Exact ports of the reference's statistics golden tests
(gossip_stats.rs:2007-2428): stranded, RMR, hops, coverage, branching."""

from gossip_sim_tpu.constants import LAMPORTS_PER_SOL, UNREACHED
from gossip_sim_tpu.identity import Pubkey, pubkey_new_unique
from gossip_sim_tpu.oracle.cluster import Cluster, Node
from gossip_sim_tpu.oracle.rustrng import ChaChaRng
from gossip_sim_tpu.stats import GossipStats

MAX_STAKE = (1 << 20) * LAMPORTS_PER_SOL


def seeded_stakes(n_extra, seed=189):
    nodes = [pubkey_new_unique() for _ in range(n_extra)]
    rng = ChaChaRng.from_seed_byte(seed)
    pubkey = pubkey_new_unique()
    stakes = {pk: rng.gen_range_u64(1, MAX_STAKE) for pk in nodes}
    stakes[pubkey] = rng.gen_range_u64(1, MAX_STAKE)
    return stakes, pubkey, rng


P = Pubkey.from_string


def test_stranded():
    # gossip_stats.rs:2007-2072
    stakes, _, _ = seeded_stakes(9)
    stats = GossipStats()
    stranded = [
        P("11111113pNDtm61yGF8j2ycAwLEPsuWQXobye5qDR"),
        P("11111114DhpssPJgSi1YU7hCMfYt1BJ334YgsffXm"),
        P("11111114d3RrygbPdAtMuFnDmzsN8T5fYKVQ7FVr7"),
        P("111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT"),
    ]
    stats.insert_stranded_nodes(stranded, stakes)
    stats.stranded_node_collection.calculate_stats()
    s = stats.get_stranded_stats()
    assert s[0] == 4
    assert s[1] == 0.4
    assert s[2] == 4.0
    assert s[3] == 1.0
    assert s[4] == 1.0
    assert s[5] == 645017127080371.25
    assert s[6] == 724161057685112.0
    assert s[7] == 1017190976849038
    assert s[8] == 114555416102223
    assert s[9] == 645017127080371.25
    assert s[10] == 724161057685112.0

    for _ in range(4):
        stranded.append(P("11111113R2cuenjG5nFubqX9Wzuukdin2YfGQVzu5"))
        stranded.append(P("11111112D1oxKts8YPdTJRG5FzxTNpMtWmq8hkVx3"))
        stranded.append(P("111111131h1vYVSYuKP6AhS86fbRdMw9XHiZAvAaj"))
        stranded.append(P("1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM"))
    for _ in range(7):
        stranded.append(P("11111113R2cuenjG5nFubqX9Wzuukdin2YfGQVzu5"))
        stranded.append(P("111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT"))
        stranded.append(P("1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM"))
        stranded.append(P("11111114DhpssPJgSi1YU7hCMfYt1BJ334YgsffXm"))

    stats.insert_stranded_nodes(stranded, stakes)
    stats.stranded_node_collection.calculate_stats()
    s = stats.get_stranded_stats()
    assert s[0] == 52
    assert s[1] == 5.2
    assert s[2] == 26.0
    assert s[3] == 6.50
    assert s[4] == 6.50
    assert s[5] == 617812196595019.00
    assert s[6] == 623567922929968.5
    assert s[7] == 1017190976849038
    assert s[8] == 114555416102223
    assert s[9] == 615709255382738.9
    assert s[10] == 585038762479069.0


def test_rmr():
    # gossip_stats.rs:2074-2157: RMR goldens over a 100-iteration seeded run.
    #
    # The reference's asserted values (2.8 at iter 0, 2.0 at iter 95, mean
    # 2.4800000000000044) are inconsistent with its committed engine: with 6
    # nodes and fanout 2, m <= 12 per round (one increment per push edge,
    # gossip.rs:571), yet 2.8 requires m=19.  They are stale goldens from a
    # legacy m-counting, m_legacy = edges + duplicate-deliveries = 2m - (n-1):
    # 2*12-5=19 -> 2.8, 2*10-5=15 -> 2.0.  We assert BOTH: the committed
    # formula's values, and the reference goldens via the legacy formula —
    # matching them exactly proves the prune/convergence dynamics are
    # identical round-for-round.
    PUSH_FANOUT, ACTIVE_SET_SIZE = 2, 12
    PRUNE_STAKE_THRESHOLD, MIN_INGRESS_NODES = 0.15, 2
    CHANCE_TO_ROTATE, GOSSIP_ITERATIONS = 0.2, 100
    stakes, origin, rng = seeded_stakes(5)
    nodes = sorted((Node(pk, s) for pk, s in stakes.items()),
                   key=lambda nd: nd.pubkey.raw)
    for node in nodes:
        node.initialize_gossip(rng, stakes, ACTIVE_SET_SIZE)
    stats = GossipStats()
    legacy_stats = GossipStats()
    cluster = Cluster(PUSH_FANOUT)
    rot_rng = ChaChaRng.from_seed_byte(11)
    node_map = {nd.pubkey: nd for nd in nodes}
    for _ in range(GOSSIP_ITERATIONS):
        cluster.run_gossip(origin, stakes, node_map)
        rmr, m, n = cluster.relative_message_redundancy()
        stats.insert_rmr(rmr)
        legacy_stats.insert_rmr((2 * m - (n - 1)) / (n - 1) - 1.0)
        cluster.consume_messages(origin, nodes)
        cluster.send_prunes(origin, nodes, PRUNE_STAKE_THRESHOLD,
                            MIN_INGRESS_NODES, stakes)
        cluster.prune_connections(node_map, stakes)
        cluster.chance_to_rotate(rot_rng, nodes, ACTIVE_SET_SIZE, stakes,
                                 CHANCE_TO_ROTATE)
    # Reference goldens (gossip_stats.rs:2146-2154) via the legacy formula:
    assert legacy_stats.get_rmr_by_index(0) == 2.8
    assert legacy_stats.get_rmr_by_index(95) == 2.0
    legacy_stats.rmr_stats.calculate_stats()
    mean, median, mx, mn = legacy_stats.get_rmr_stats()
    # Reference float dust (2.4800000000000044) came from the legacy engine's
    # internal accumulation; identical-ops summation over {2.8 x60, 2.0 x40}
    # gives exactly 2.48.
    assert abs(mean - 2.4800000000000044) < 1e-12
    assert (median, mx, mn) == (2.8, 2.8, 2.0)
    # Committed-formula values for the same run:
    assert stats.get_rmr_by_index(0) == 1.4
    assert stats.get_rmr_by_index(95) == 1.0
    stats.rmr_stats.calculate_stats()
    assert stats.get_rmr_stats() == (1.2400000000000022, 1.4, 1.4, 1.0)


def test_hops():
    # gossip_stats.rs:2159-2258
    stats = GossipStats()
    d = {
        P("11111113pNDtm61yGF8j2ycAwLEPsuWQXobye5qDR"): UNREACHED,
        P("11111114DhpssPJgSi1YU7hCMfYt1BJ334YgsffXm"): UNREACHED,
        P("11111114d3RrygbPdAtMuFnDmzsN8T5fYKVQ7FVr7"): UNREACHED,
        P("111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT"): UNREACHED,
        P("11111113R2cuenjG5nFubqX9Wzuukdin2YfGQVzu5"): 0,
        P("11111112D1oxKts8YPdTJRG5FzxTNpMtWmq8hkVx3"): 1,
        P("111111131h1vYVSYuKP6AhS86fbRdMw9XHiZAvAaj"): 1,
        P("1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM"): 2,
        P("11111112cMQwSC9qirWGjZM6gLGwW69X22mqwLLGP"): 2,
        P("1111111ogCyDbaRMvkdsHB3qfdyFYaG1WtRUAfdh"): 3,
    }
    stats.insert_hops_stat(d)
    assert stats.get_per_hop_stats_by_index(0) == (1.8, 2.0, 3, 1)

    d2 = {k: UNREACHED for k in list(d)[:6]}
    d2.update({
        P("11111113R2cuenjG5nFubqX9Wzuukdin2YfGQVzu5"): 0,
        P("11111112D1oxKts8YPdTJRG5FzxTNpMtWmq8hkVx3"): 1,
        P("111111131h1vYVSYuKP6AhS86fbRdMw9XHiZAvAaj"): 1,
        P("1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM"): 2,
    })
    stats.insert_hops_stat(d2)
    assert stats.get_per_hop_stats_by_index(1) == \
        (1.3333333333333333, 1.0, 2, 1)

    d3 = {k: UNREACHED for k in list(d)[:7]}
    d3.update({
        P("1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM"): UNREACHED,
        P("11111113R2cuenjG5nFubqX9Wzuukdin2YfGQVzu5"): 0,
        P("11111112D1oxKts8YPdTJRG5FzxTNpMtWmq8hkVx3"): 1,
        P("1111111ogCyDbaRMvkdsHB3qfdyFYaG1WtRUAfdh"): 6,
    })
    stats.insert_hops_stat(d3)
    assert stats.get_per_hop_stats_by_index(2) == (3.5, 3.5, 6, 1)

    stats.hops_stats.aggregate_hop_stats()
    assert stats.get_aggregate_hop_stats() == (2.0, 1.5, 6, 1)
    assert stats.get_last_delivery_hop_stats() == \
        (3.6666666666666665, 3.0, 6, 2)


def test_coverage():
    # gossip_stats.rs:2261-2358 (coverage over a 10-node stake map)
    stakes, _, _ = seeded_stakes(9)
    stats = GossipStats()

    def calc_coverage(distances):
        visited = sum(1 for v in distances.values() if v != UNREACHED)
        return visited / len(stakes)

    d = {P("11111113R2cuenjG5nFubqX9Wzuukdin2YfGQVzu5"): 0}
    for s in ["11111112D1oxKts8YPdTJRG5FzxTNpMtWmq8hkVx3",
              "111111131h1vYVSYuKP6AhS86fbRdMw9XHiZAvAaj"]:
        d[P(s)] = 1
    for s in ["1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM",
              "11111112cMQwSC9qirWGjZM6gLGwW69X22mqwLLGP"]:
        d[P(s)] = 2
    d[P("1111111ogCyDbaRMvkdsHB3qfdyFYaG1WtRUAfdh")] = 3
    for s in ["11111113pNDtm61yGF8j2ycAwLEPsuWQXobye5qDR",
              "11111114DhpssPJgSi1YU7hCMfYt1BJ334YgsffXm",
              "11111114d3RrygbPdAtMuFnDmzsN8T5fYKVQ7FVr7",
              "111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT"]:
        d[P(s)] = UNREACHED
    cov = calc_coverage(d)
    assert cov == 0.6
    stats.insert_coverage(cov)
    stats.coverage_stats.calculate_stats()
    assert stats.get_coverage_stats() == (0.6, 0.6, 0.6, 0.6)

    stats.insert_coverage(0.4)
    stats.coverage_stats.calculate_stats()
    assert stats.get_coverage_stats() == (0.5, 0.5, 0.6, 0.4)

    stats.insert_coverage(0.2)
    stats.coverage_stats.calculate_stats()
    m, md, mx, mn = stats.get_coverage_stats()
    assert m == 0.4000000000000001
    assert (md, mx, mn) == (0.4, 0.6, 0.2)


def test_branching_factors():
    # gossip_stats.rs:2361-2428
    stats = GossipStats()
    n = [P(s) for s in [
        "11111113pNDtm61yGF8j2ycAwLEPsuWQXobye5qDR",
        "111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT",
        "11111112cMQwSC9qirWGjZM6gLGwW69X22mqwLLGP",
        "1111111ogCyDbaRMvkdsHB3qfdyFYaG1WtRUAfdh",
        "11111114d3RrygbPdAtMuFnDmzsN8T5fYKVQ7FVr7",
        "11111114DhpssPJgSi1YU7hCMfYt1BJ334YgsffXm",
        "111111131h1vYVSYuKP6AhS86fbRdMw9XHiZAvAaj",
        "1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM",
    ]]
    pushes = {k: set() for k in n}
    pushes[n[0]] = {n[3], n[7], n[4]}
    pushes[n[1]] = {n[5], n[6]}
    pushes[n[2]] = {n[6]}
    pushes[n[3]] = {n[1]}
    pushes[n[4]] = {n[5]}
    pushes[n[6]] = {n[5]}
    pushes[n[7]] = {n[2]}
    stats.calculate_outbound_branching_factor(pushes)
    assert stats.get_outbound_branching_factor_by_index(0) == 1.25
