"""Concurrent-traffic subsystem tests (ISSUE 10).

Covers the traffic.py / engine/traffic.py contract:

* **1k-node oracle parity** — under packet loss AND churn with rotation
  ON (hash-driven, so no forced-active-set scaffolding), M >= 16 value
  slots and both queue caps active, the loop-based ``TrafficOracle`` must
  match the sort-routed engine bit-for-bit: every per-round counter, the
  per-value holder/hop tables, the retirement records, and the shared
  active set itself.
* **Lifecycle** — slot recycling, monotone value ids, stall-based
  retirement, injection determinism + stake weighting.
* **Gating** — traffic off (M=1, caps off) never engages the subsystem;
  queue-cap knobs against a traffic-less static raise (core's knob-gate
  guard); traffic+pull and traffic+fail_at are rejected.
* **Compile-once sweeps** — stepping traffic_rate / queue caps on a warm
  executable adds zero compiles; ``run_traffic_lanes`` is bit-identical
  per lane to serial runs.
* **Queue-cap sanity** — unlimited ingress delivers at least as much
  per-value coverage as a tight cap (the traffic_smoke gate's property).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.engine import make_cluster_tables
from gossip_sim_tpu.engine.params import EngineKnobs, EngineParams
from gossip_sim_tpu.engine.traffic import (broadcast_traffic_state,
                                           clear_traffic_compile_cache,
                                           device_traffic_tables,
                                           init_traffic_state,
                                           run_traffic_lanes,
                                           run_traffic_rounds,
                                           traffic_compiled_cache_size,
                                           traffic_lane_state)
from gossip_sim_tpu.traffic import (TrafficOracle, build_shared_active_set,
                                    traffic_tables)

SCALARS = ["injected", "inject_dropped", "live", "sends", "deferred",
           "failed_target", "suppressed", "dropped", "arrived",
           "queue_dropped", "accepted", "delivered", "redundant",
           "prunes_sent", "retired", "converged", "hop_clamped",
           "qdepth_max", "inflow_max"]


def _stakes(n, seed=3):
    rng = np.random.default_rng(seed)
    return rng.choice(np.arange(1, 50 * n), size=n,
                      replace=False).astype(np.int64) * 10**6


def _oracle_from(params: EngineParams, stakes, seed):
    return TrafficOracle(
        stakes, seed=seed, impair_seed=params.impair_seed,
        traffic_values=params.traffic_values,
        traffic_rate=params.traffic_rate,
        node_ingress_cap=params.node_ingress_cap,
        node_egress_cap=params.node_egress_cap,
        traffic_stall_rounds=params.traffic_stall_rounds,
        push_fanout=params.push_fanout,
        active_set_size=params.active_set_size,
        init_draws=params.init_draws, k_inbound=params.k_inbound,
        received_cap=params.received_cap, rc_slots=params.rc_slots,
        min_num_upserts=params.min_num_upserts,
        prune_stake_threshold=params.prune_stake_threshold,
        min_ingress_nodes=params.min_ingress_nodes,
        probability_of_rotation=params.probability_of_rotation,
        rot_tries=params.rot_tries, hist_bins=params.hist_bins,
        packet_loss_rate=params.packet_loss_rate,
        churn_fail_rate=params.churn_fail_rate,
        churn_recover_rate=params.churn_recover_rate,
        partition_at=params.partition_at, heal_at=params.heal_at)


def _run_engine(params, stakes, rounds, seed, **kw):
    tables = make_cluster_tables(stakes)
    tt = device_traffic_tables(stakes)
    state = init_traffic_state(stakes, params, seed)
    state, rows = run_traffic_rounds(params, tables, tt, state, rounds,
                                     **kw)
    return state, jax.tree_util.tree_map(np.asarray, rows)


def _engine_records(rows, r):
    recs = []
    for m in np.nonzero(rows["ret_mask"][r])[0]:
        recs.append(dict(vid=int(rows["ret_vid"][r, m]),
                         origin=int(rows["ret_origin"][r, m]),
                         birth=int(rows["ret_birth"][r, m]),
                         holders=int(rows["ret_holders"][r, m]),
                         m=int(rows["ret_m"][r, m]),
                         converged=bool(rows["ret_full"][r, m]),
                         hops_sum=int(rows["ret_hops_sum"][r, m])))
    return sorted(recs, key=lambda d: d["vid"])


def _oracle_records(tr):
    recs = [dict(vid=x["vid"], origin=x["origin"], birth=x["birth"],
                 holders=x["holders"], m=x["m"], converged=x["converged"],
                 hops_sum=int(round(x["mean_hop"] * x["holders"])))
            for x in tr.records]
    return sorted(recs, key=lambda d: d["vid"])


def _assert_parity(params, stakes, rounds, seed):
    state, rows = _run_engine(params, stakes, rounds, seed, detail=True)
    oracle = _oracle_from(params, stakes, seed)
    np.testing.assert_array_equal(
        build_shared_active_set(stakes, seed, params.active_set_size,
                                params.init_draws),
        oracle.active, err_msg="init active set")
    for r in range(rounds):
        tr = oracle.run_round(r)
        for k in SCALARS:
            assert int(rows[k][r]) == getattr(tr, k), f"{k} @ round {r}"
        for m in range(oracle.mv):
            sl = oracle.slots[m]
            assert bool(rows["live_mask"][r, m]) == (sl is not None), \
                f"live @ round {r} slot {m}"
            if sl is None:
                continue
            np.testing.assert_array_equal(
                rows["t_holder"][r, m], sl["holder"],
                err_msg=f"holder @ round {r} slot {m}")
            np.testing.assert_array_equal(
                rows["t_hop"][r, m], np.where(sl["holder"], sl["hop"], -1),
                err_msg=f"hop @ round {r} slot {m}")
        np.testing.assert_array_equal(
            rows["node_deferred"][r], tr.node_deferred,
            err_msg=f"node_deferred @ round {r}")
        np.testing.assert_array_equal(
            rows["node_queue_dropped"][r], tr.node_queue_dropped,
            err_msg=f"node_queue_dropped @ round {r}")
        assert _engine_records(rows, r) == _oracle_records(tr), \
            f"retirement records @ round {r}"
    np.testing.assert_array_equal(np.asarray(state.active), oracle.active,
                                  err_msg="final shared active set")
    np.testing.assert_array_equal(np.asarray(state.failed), oracle.failed,
                                  err_msg="final churn mask")
    assert int(state.next_vid) == oracle.next_vid
    return state, rows, oracle


class TestOracleParity:
    def test_small_cluster_full_lifecycle(self):
        """64 nodes, aggressive knobs: values converge, stall-retire and
        recycle within 10 rounds; every quantity matches bit-for-bit."""
        n = 64
        params = EngineParams(
            num_nodes=n, traffic_values=4, traffic_rate=2,
            node_ingress_cap=6, node_egress_cap=10,
            traffic_stall_rounds=2, warm_up_rounds=0,
            probability_of_rotation=0.2, impair_seed=99,
            packet_loss_rate=0.15, churn_fail_rate=0.03,
            churn_recover_rate=0.3, min_num_upserts=3).validate()
        state, rows, oracle = _assert_parity(params, _stakes(n), 10, seed=7)
        assert int(state.next_vid) > 0
        # the regime must actually exercise retirement + recycling
        assert rows["retired"].sum() > 0
        assert rows["injected"].sum() > int(params.traffic_values)

    @pytest.mark.slow  # tier-1 budget; tools/traffic_smoke gate covers this
    def test_exact_parity_1k_nodes_m16_under_faults(self):
        """The ISSUE 10 acceptance gate: >= 1k nodes, M >= 16 in-flight
        values, both queue caps active, packet loss AND churn, shared
        rotation ON — engine and oracle bit-identical every round."""
        n = 1024
        params = EngineParams(
            num_nodes=n, traffic_values=16, traffic_rate=3,
            node_ingress_cap=24, node_egress_cap=48,
            traffic_stall_rounds=3, warm_up_rounds=0,
            probability_of_rotation=0.05, impair_seed=99,
            packet_loss_rate=0.15, churn_fail_rate=0.03,
            churn_recover_rate=0.3, min_num_upserts=5).validate()
        _, rows, _ = _assert_parity(params, _stakes(n), 6, seed=7)
        # contention is real in this regime, not a degenerate pass
        assert rows["queue_dropped"].sum() > 0
        assert rows["deferred"].sum() > 0
        assert rows["dropped"].sum() > 0


class TestLifecycle:
    N = 48
    BASE = dict(num_nodes=48, traffic_values=3, traffic_rate=1,
                warm_up_rounds=0, traffic_stall_rounds=2,
                min_num_upserts=4, node_ingress_cap=4)

    def test_slot_recycling_and_monotone_vids(self):
        params = EngineParams(**self.BASE).validate()
        stakes = _stakes(self.N)
        state, rows = _run_engine(params, stakes, 20, seed=5, detail=True)
        vids = []
        for r in range(20):
            vids.extend(d["vid"] for d in _engine_records(rows, r))
        assert len(vids) > int(params.traffic_values), \
            "slots never recycled"
        assert vids == sorted(vids)
        assert len(set(vids)) == len(vids)
        assert int(state.next_vid) >= len(vids)
        # a retired slot's record is complete and coherent
        rec = _engine_records(rows, int(np.nonzero(
            rows["ret_mask"].any(axis=1))[0][0]))[0]
        assert 1 <= rec["holders"] <= self.N
        assert rec["birth"] >= 0

    def test_injection_deterministic_and_stake_weighted(self):
        params = EngineParams(**self.BASE).validate()
        stakes = _stakes(self.N)
        _, rows_a = _run_engine(params, stakes, 12, seed=5, detail=True)
        _, rows_b = _run_engine(params, stakes, 12, seed=5, detail=True)
        for k in ("ret_vid", "ret_origin", "t_holder"):
            np.testing.assert_array_equal(rows_a[k], rows_b[k])
        # stake weighting: across many draws, the top-stake half of the
        # cluster must win more injections than the bottom half
        oracle = _oracle_from(params, stakes, seed=5)
        origins = []
        for it in range(400):
            oracle.slots = [None] * oracle.mv   # always room: pure schedule
            oracle.inject(it)
            origins.extend(s["origin"] for s in oracle.slots
                           if s is not None)
        med = np.median(stakes)
        high = sum(stakes[o] >= med for o in origins)
        assert high > len(origins) * 0.6

    def test_stranded_origin_value_never_counted_covered(self):
        """A value whose origin is churn-failed at birth makes no progress,
        stall-retires, and reports coverage 1/N — never 'converged'."""
        n = self.N
        params = EngineParams(**{**self.BASE, "churn_fail_rate": 1.0,
                                 "churn_recover_rate": 0.0}).validate()
        stakes = _stakes(n)
        _, rows = _run_engine(params, stakes, 6, seed=5, detail=True)
        recs = [d for r in range(6) for d in _engine_records(rows, r)]
        assert recs, "nothing retired"
        assert all(not d["converged"] for d in recs)
        assert all(d["holders"] == 1 for d in recs)


class TestGating:
    def test_traffic_off_by_default(self):
        p = EngineParams(num_nodes=32)
        assert not p.has_traffic
        assert p.static_part().traffic_slots == 0

    def test_caps_engage_traffic_even_at_m1(self):
        p = EngineParams(num_nodes=32, node_ingress_cap=8)
        assert p.has_traffic
        assert p.static_part().traffic_slots == 1

    def test_cap_knobs_against_trafficless_static_raise(self):
        p = EngineParams(num_nodes=32).validate()
        static, kn = p.split()
        bad = kn._replace(node_ingress_cap=np.int32(4))
        stakes = _stakes(32)
        tables = make_cluster_tables(stakes)
        origins = jnp.asarray([0], jnp.int32)
        from gossip_sim_tpu.engine import init_state, run_rounds
        state = init_state(jax.random.PRNGKey(0), tables, origins, p)
        with pytest.raises(ValueError, match="has_traffic"):
            run_rounds(static, tables, origins, state, 1, knobs=bad)

    def test_traffic_rejects_pull_and_fail_at(self):
        with pytest.raises(AssertionError, match="pull"):
            EngineParams(num_nodes=32, traffic_values=4,
                         gossip_mode="push-pull").validate()
        with pytest.raises(AssertionError, match="fail_at"):
            EngineParams(num_nodes=32, traffic_values=4, fail_at=2,
                         fail_fraction=0.5).validate()


class TestCompileOnceAndLanes:
    N = 48
    BASE = dict(num_nodes=48, traffic_values=4, traffic_rate=1,
                warm_up_rounds=0, node_ingress_cap=8, node_egress_cap=16,
                min_num_upserts=4)

    def test_traffic_knob_sweep_compiles_once(self):
        clear_traffic_compile_cache()
        stakes = _stakes(self.N)
        params = EngineParams(**self.BASE).validate()
        tables = make_cluster_tables(stakes)
        tt = device_traffic_tables(stakes)
        static, kn0 = params.split()
        compiles = []
        for rate, icap, ecap in [(1, 8, 16), (2, 8, 16), (2, 4, 16),
                                 (3, 12, 8)]:
            kn = kn0._replace(traffic_rate=np.int32(rate),
                              node_ingress_cap=np.int32(icap),
                              node_egress_cap=np.int32(ecap))
            state = init_traffic_state(stakes, params, seed=5)
            before = traffic_compiled_cache_size()
            run_traffic_rounds(static, tables, tt, state, 3, knobs=kn)
            compiles.append(traffic_compiled_cache_size() - before)
        assert compiles[0] == 1, "first call must compile"
        assert compiles[1:] == [0, 0, 0], \
            f"knob steps recompiled: {compiles}"

    def test_lanes_bit_exact_vs_serial(self):
        stakes = _stakes(self.N)
        params = EngineParams(**self.BASE).validate()
        tables = make_cluster_tables(stakes)
        tt = device_traffic_tables(stakes)
        static, kn0 = params.split()
        lane_caps = [0, 4, 12]
        knob_list = [kn0._replace(node_ingress_cap=np.int32(c))
                     for c in lane_caps]
        from gossip_sim_tpu.engine.lanes import stack_knobs
        lanes = broadcast_traffic_state(
            init_traffic_state(stakes, params, seed=5), len(lane_caps))
        lstate, lrows = run_traffic_lanes(static, tables, tt, lanes,
                                          stack_knobs(knob_list), 6,
                                          detail=True)
        lrows = jax.tree_util.tree_map(np.asarray, lrows)
        for i, kn in enumerate(knob_list):
            state = init_traffic_state(stakes, params, seed=5)
            sstate, srows = run_traffic_rounds(static, tables, tt, state, 6,
                                               detail=True, knobs=kn)
            srows = jax.tree_util.tree_map(np.asarray, srows)
            for k in SCALARS + ["ret_vid", "ret_mask", "t_holder", "t_hop"]:
                np.testing.assert_array_equal(
                    lrows[k][:, i], srows[k],
                    err_msg=f"lane {i} row {k} diverges from serial")
            lane_st = traffic_lane_state(lstate, i)
            for f, a, b in zip(lane_st._fields, lane_st, sstate):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"lane {i} state field {f}")

    def test_ingress_cap_monotone_coverage(self):
        """Per-value delivered volume must not shrink when the ingress cap
        is lifted (prunes disabled so no feedback loop) — the property the
        traffic_smoke CI gate checks end-to-end."""
        stakes = _stakes(self.N)
        base = dict(self.BASE, min_num_upserts=10**6,
                    node_egress_cap=0)
        totals = []
        for cap in (1, 2, 0):
            params = EngineParams(**{**base, "node_ingress_cap": cap}
                                  ).validate()
            _, rows = _run_engine(params, stakes, 8, seed=5)
            totals.append(int(rows["delivered"].sum()))
        assert totals[0] <= totals[1] <= totals[2], totals


def test_shared_active_set_properties():
    stakes = _stakes(96)
    active = build_shared_active_set(stakes, seed=11, active_set_size=12,
                                     init_draws=64)
    n = 96
    assert active.shape == (n, 12)
    for i in range(n):
        row = active[i][active[i] < n]
        assert len(set(row.tolist())) == len(row), "duplicate peers"
        assert i not in row, "self in own active set"
    # deterministic
    np.testing.assert_array_equal(
        active, build_shared_active_set(stakes, 11, 12, 64))
    # stake weighting: the top-stake node appears far more often than the
    # bottom-stake node across all rows
    top = int(np.argmax(stakes))
    bot = int(np.argmin(stakes))
    assert (active == top).sum() > (active == bot).sum()


def test_traffic_tables_match_pull_cdf():
    from gossip_sim_tpu.pull import pull_class_tables
    stakes = _stakes(64)
    tt = traffic_tables(stakes)
    pt = pull_class_tables(stakes)
    np.testing.assert_array_equal(tt.perm, pt.perm)
    np.testing.assert_array_equal(tt.cdf, pt.cdf)


# --------------------------------------------------------------------------
# CLI path (cli.run_traffic): backend parity, lane sweeps, resume, report
# --------------------------------------------------------------------------

def _traffic_cli_config(**kw):
    from gossip_sim_tpu.config import Config, StepSize, Testing
    base = dict(num_synthetic_nodes=48, traffic_values=3, traffic_rate=1,
                node_ingress_cap=4, node_egress_cap=8,
                packet_loss_rate=0.1, churn_fail_rate=0.02,
                churn_recover_rate=0.3, gossip_iterations=10,
                warm_up_rounds=2, seed=9,
                step_size=StepSize.parse("1"))
    base.update(kw)
    return Config(**base)


def _run_traffic_cli(config):
    from gossip_sim_tpu.cli import run_traffic
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.obs import get_registry
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.traffic import TrafficStatsCollection
    reset_unique_pubkeys()
    get_registry().reset()
    coll = TrafficStatsCollection()
    dpq = DatapointQueue()
    summary = run_traffic(config, "", dpq, "0", collection=coll)
    return summary, coll, dpq.drain_deterministic_lines()


class TestTrafficCLI:
    def test_backend_parity_and_wire_lines(self):
        """tpu and oracle backends produce bit-identical TrafficStats
        parity snapshots AND identical deterministic Influx payloads
        under loss + churn + both queue caps."""
        s_t, c_t, w_t = _run_traffic_cli(_traffic_cli_config())
        s_o, c_o, w_o = _run_traffic_cli(
            _traffic_cli_config(backend="oracle"))
        assert (c_t.collection[0].parity_snapshot()
                == c_o.collection[0].parity_snapshot())
        assert w_t == w_o
        assert any(ln.startswith("sim_traffic,") for ln in w_t)
        assert any(ln.startswith("sim_traffic_summary,") for ln in w_t)
        assert s_t["traffic"] == s_o["traffic"]

    @pytest.mark.slow
    def test_lane_sweep_matches_serial(self):
        """A node-ingress-cap sweep through --sweep-lanes is bit-exact
        per point vs the serial sweep (stats + wire payloads).  Heavy
        (three extra compiles): slow-marked — tier-1 keeps the
        engine-level lane parity (TestCompileOnceAndLanes) and the
        traffic_smoke gate covers the CLI stack."""
        from gossip_sim_tpu.config import Testing
        base = dict(test_type=Testing.NODE_INGRESS_CAP,
                    num_simulations=3, node_ingress_cap=2,
                    churn_fail_rate=0.0, churn_recover_rate=0.0)
        _, c_serial, w_serial = _run_traffic_cli(_traffic_cli_config(**base))
        s_lane, c_lane, w_lane = _run_traffic_cli(
            _traffic_cli_config(sweep_lanes=3, **base))
        assert s_lane["sweep_lanes"] == 3
        assert len(c_lane.collection) == 3
        for i, (a, b) in enumerate(zip(c_serial.collection,
                                       c_lane.collection)):
            assert a.parity_snapshot() == b.parity_snapshot(), f"point {i}"
        assert w_serial == w_lane

    @pytest.mark.slow
    def test_checkpoint_resume_bit_exact(self, tmp_path):
        """v6 traffic checkpoint: interrupt at iteration 9, resume to 16
        — stats parity snapshot identical to the uninterrupted run
        (three full CLI runs; the fast save/restore roundtrip lives in
        test_checkpoint.py)."""
        ck = str(tmp_path / "traffic.npz")
        _, c_full, _ = _run_traffic_cli(
            _traffic_cli_config(gossip_iterations=16))
        _run_traffic_cli(_traffic_cli_config(gossip_iterations=9,
                                             checkpoint_path=ck))
        _, c_res, _ = _run_traffic_cli(
            _traffic_cli_config(gossip_iterations=16, checkpoint_path=ck,
                                resume_path=ck))
        assert (c_full.collection[0].parity_snapshot()
                == c_res.collection[0].parity_snapshot())

    def test_report_summary_keys(self):
        s, coll, _ = _run_traffic_cli(_traffic_cli_config())
        t = s["traffic"]
        for k in ("values_injected", "values_retired", "values_converged",
                  "values_unfinished", "queue_deferred", "queue_dropped",
                  "value_latency_mean", "value_coverage_mean",
                  "value_rmr_mean", "hop_clamped", "qdepth_max"):
            assert k in t, k
        # the summary is exactly the last point's TrafficStats.summary()
        want = dict(coll.collection[-1].summary())
        assert t == want

    def test_m1_caps_off_is_fully_gated_out(self):
        """traffic_values=1 with caps off never reroutes to the traffic
        engine: Config.traffic_on is False and the EngineParams compile
        key carries zero traffic geometry — the pre-traffic bit-identity
        contract (pull's mode=push precedent)."""
        from gossip_sim_tpu.cli import _engine_params
        cfg = _traffic_cli_config(traffic_values=1, node_ingress_cap=0,
                                  node_egress_cap=0)
        assert not cfg.traffic_on
        p = _engine_params(cfg, 48)
        assert not p.has_traffic
        assert p.static_part().traffic_slots == 0

    def test_trace_dir_writes_v3_traffic_trace(self, tmp_path):
        """--trace-dir on a traffic run writes a valid schema-v3 trace
        with the value-id column (regression: the TraceWriter used to
        read an EngineStatic-only property off EngineParams and crashed
        before round 1)."""
        from gossip_sim_tpu.obs.trace import (TRACE_SCHEMA, load_trace,
                                              validate_trace_dir)
        d = str(tmp_path / "trace")
        _run_traffic_cli(_traffic_cli_config(trace_dir=d))
        assert validate_trace_dir(d) == []
        tr = load_trace(d)
        assert tr.manifest["schema"] == TRACE_SCHEMA
        assert tr.manifest["traffic_slots"] == 3
        rr = tr.at(int(tr.rounds[0]))
        assert rr["value_id"].shape == (3,)
        assert (rr["value_id"] >= -1).all()

    def test_sweep_rejects_shared_checkpoint(self, tmp_path):
        """A multi-point traffic sweep under --checkpoint-path/--resume
        must be rejected loudly: every point would share ONE state file
        (the lane blocker's 'single runs only' contract, enforced on the
        serial path too)."""
        from gossip_sim_tpu.config import Testing
        cfg = _traffic_cli_config(test_type=Testing.TRAFFIC_RATE,
                                  num_simulations=2,
                                  checkpoint_path=str(tmp_path / "x.npz"))
        with pytest.raises(ValueError, match="single traffic runs only"):
            _run_traffic_cli(cfg)

    def test_sweep_report_aggregates_and_traces_per_point(self, tmp_path):
        """On a sweep, stats.traffic sums EVERY point's counters (not
        last-point-only) and --trace-dir writes one valid per-point
        subdir (the PR 3 generic-sweep layout)."""
        from gossip_sim_tpu.config import Testing
        from gossip_sim_tpu.obs.trace import validate_trace_dir
        d = str(tmp_path / "trace")
        s, coll, _ = _run_traffic_cli(
            _traffic_cli_config(test_type=Testing.TRAFFIC_RATE,
                                num_simulations=2, trace_dir=d))
        sums = [st.summary() for st in coll.collection]
        assert len(s["traffic_points"]) == 2
        for k in ("values_injected", "values_retired", "queue_dropped",
                  "measured_rounds"):
            assert s["traffic"][k] == sums[0][k] + sums[1][k], k
        for sub in ("sim000", "sim001"):
            assert validate_trace_dir(os.path.join(d, sub)) == []


def test_stranded_value_root_caused_by_explain_stranded():
    """ISSUE 10 satellite: a value whose origin is pruned off must be
    root-caused by stats/edges.py explain-stranded (cause 'pruned'), not
    silently counted as covered.  Built from the engine's v3 trace rows:
    per-value slices feed explain_stranded directly."""
    from gossip_sim_tpu.stats.edges import (CAUSE_NO_SENDERS, CAUSE_PRUNED,
                                            explain_stranded)
    n = 48
    stakes = _stakes(n)
    params = EngineParams(num_nodes=n, traffic_values=2, traffic_rate=1,
                          warm_up_rounds=0, traffic_stall_rounds=4,
                          probability_of_rotation=0.0,
                          min_num_upserts=10**6).validate()
    tables = make_cluster_tables(stakes)
    tt = device_traffic_tables(stakes)
    state = init_traffic_state(stakes, params, seed=5)
    # poison slot 0's value before it is injected is impossible — instead
    # run one round (value 0 injected at its origin), then prune the
    # origin's every shared slot for value 0 and keep running
    state, rows0 = run_traffic_rounds(params, tables, tt, state, 1,
                                      detail=True, trace=True)
    origin0 = int(np.asarray(rows0["trace_origin"])[0, 0])
    assert origin0 >= 0
    import jax.numpy as jnp
    pruned = np.array(state.pruned)
    pruned[0, origin0, :] = True
    # also erase what round 0 already delivered so the value is origin-only
    holder = np.zeros((2, n), bool)
    hop = np.full((2, n), -1, np.int32)
    v_origin = np.asarray(state.v_origin)
    for m in range(2):
        if v_origin[m] < n:
            holder[m, v_origin[m]] = True
            hop[m, v_origin[m]] = 0
    state = state._replace(pruned=jnp.asarray(pruned),
                           v_holder=jnp.asarray(holder),
                           v_hop=jnp.asarray(hop))
    state, rows = run_traffic_rounds(params, tables, tt, state, 4,
                                     detail=True, trace=True)
    rows = jax.tree_util.tree_map(np.asarray, rows)
    # the poisoned value makes no progress and stall-retires un-converged
    recs = [d for r in range(4) for d in _engine_records(rows, r)]
    poisoned = [d for d in recs if d["origin"] == origin0]
    assert poisoned and all(not d["converged"] for d in poisoned)
    assert all(d["holders"] == 1 for d in poisoned)
    # root-cause the first post-poison round via the trace arrays
    r = 0
    active = rows["trace_active"][r]
    out = explain_stranded(
        np.where(active >= 0, active, n),      # explain expects N = empty?
        rows["trace_pruned"][r, 0],
        rows["trace_peers"][r, 0], rows["trace_code"][r, 0],
        rows["t_hop"][r, 0], rows["trace_failed"][r], origin0)
    by_node = {e["node"]: e for e in out}
    # every node the origin's active set pointed at is explained as
    # pruned; nodes nobody points at as no_potential_senders
    origin_peers = [p for p in active[origin0] if 0 <= p < n]
    assert origin_peers
    saw_pruned = False
    for p in origin_peers:
        e = by_node[int(p)]
        causes = {c["cause"] for c in e["causes"]
                  if c["sender"] == origin0}
        if causes:
            assert causes == {CAUSE_PRUNED}
            saw_pruned = True
    assert saw_pruned
    lonely = [e for e in out if CAUSE_NO_SENDERS in e["summary"]]
    assert len(lonely) + sum(1 for e in out if e["causes"]) == len(out)
