"""Exact ports of the reference's active-set golden tests
(push_active_set.rs:228-400), reproduced bit-for-bit through the
ChaCha/WeightedShuffle parity stack."""

from gossip_sim_tpu.constants import LAMPORTS_PER_SOL
from gossip_sim_tpu.identity import pubkey_new_unique
from gossip_sim_tpu.oracle.active_set import PushActiveSet, PushActiveSetEntry
from gossip_sim_tpu.oracle.rustrng import ChaChaRng

MAX_STAKE = (1 << 20) * LAMPORTS_PER_SOL


def test_push_active_set():
    # push_active_set.rs:228-339
    rng = ChaChaRng.from_seed_byte(189)
    pubkey = pubkey_new_unique()
    nodes = [pubkey_new_unique() for _ in range(20)]
    stakes = {n: rng.gen_range_u64(1, MAX_STAKE) for n in nodes}
    stakes[pubkey] = rng.gen_range_u64(1, MAX_STAKE)
    aset = PushActiveSet()
    assert all(len(e) == 0 for e in aset.entries)
    aset.rotate(rng, 5, nodes, stakes)
    assert all(len(e) == 5 for e in aset.entries)
    # every entry's filter already prunes the peer's own key (self-seed)
    for entry in aset.entries:
        for node, pruned in entry.peers.items():
            assert node in pruned

    other, origin = nodes[5], nodes[17]

    def got(origin_pk):
        return [nodes.index(n) for n in aset.get_nodes(pubkey, origin_pk, stakes)]

    assert got(origin) == [13, 5, 18, 16, 0]
    assert got(other) == [13, 18, 16, 0]

    aset.prune(pubkey, nodes[5], [origin], stakes)
    aset.prune(pubkey, nodes[3], [origin], stakes)
    aset.prune(pubkey, nodes[16], [origin], stakes)
    assert got(origin) == [13, 18, 0]
    assert got(other) == [13, 18, 16, 0]

    aset.rotate(rng, 7, nodes, stakes)
    assert all(len(e) == 7 for e in aset.entries)
    assert got(origin) == [18, 0, 7, 15, 11]
    assert got(other) == [18, 16, 0, 7, 15, 11]

    origins = [origin, other]
    aset.prune(pubkey, nodes[18], origins, stakes)
    aset.prune(pubkey, nodes[0], origins, stakes)
    aset.prune(pubkey, nodes[15], origins, stakes)
    assert got(origin) == [7, 11]
    assert got(other) == [16, 7, 11]


def test_push_active_set_entry():
    # push_active_set.rs:341-400
    rng = ChaChaRng.from_seed_byte(147)
    nodes = [pubkey_new_unique() for _ in range(20)]
    weights = [rng.gen_range_u64(1, 1000) for _ in range(20)]
    entry = PushActiveSetEntry()
    entry.rotate(rng, 5, nodes, weights)
    assert len(entry) == 5
    keys = [nodes[16], nodes[11], nodes[17], nodes[14], nodes[5]]
    assert list(entry.peers) == keys
    for origin in nodes:
        if origin not in keys:
            assert list(entry.get_nodes(origin)) == keys
        else:
            assert list(entry.get_nodes(origin, lambda n: True)) == keys
            assert list(entry.get_nodes(origin)) == \
                [k for k in keys if k != origin]
    for node, pruned in entry.peers.items():
        assert node in pruned
    # prune excludes peers from get
    origin = nodes[3]
    entry.prune(nodes[11], origin)
    entry.prune(nodes[14], origin)
    entry.prune(nodes[19], origin)  # not a peer: no-op
    assert list(entry.get_nodes(origin, lambda n: True)) == keys
    assert list(entry.get_nodes(origin)) == \
        [k for k in keys if k not in (nodes[11], nodes[14])]
    # rotation swaps in new peers, evicting oldest-first
    entry.rotate(rng, 5, nodes, weights)
    assert list(entry.peers) == [nodes[11], nodes[17], nodes[14],
                                 nodes[5], nodes[7]]
    entry.rotate(rng, 6, nodes, weights)
    assert list(entry.peers) == [nodes[17], nodes[14], nodes[5],
                                 nodes[7], nodes[1], nodes[13]]
    entry.rotate(rng, 4, nodes, weights)
    assert list(entry.peers) == [nodes[5], nodes[7], nodes[1], nodes[13]]


def test_bloom_filter_geometry_and_fp_rate():
    """Reference bloom geometry (push_active_set.rs:122-123): at n items the
    false-positive rate is ~0.1; no false negatives ever."""
    from gossip_sim_tpu.oracle.active_set import BloomFilter

    rng = ChaChaRng.from_seed_byte(7)
    n = 500
    bf = BloomFilter(n, rng)
    members = [pubkey_new_unique() for _ in range(n)]
    probes = [pubkey_new_unique() for _ in range(4000)]
    for m in members:
        bf.add(m)
    assert all(m in bf for m in members), "no false negatives"
    fp = sum(p in bf for p in probes) / len(probes)
    assert 0.04 < fp < 0.2, f"fp rate {fp} far from the 0.1 design point"
    # capped at 32768 bits like the reference
    big = BloomFilter(100_000, rng)
    assert big.m == 32768
