"""Compile-once sweep regression tests (ISSUE 4).

The EngineParams split (engine/params.py): numeric knobs are traced
EngineKnobs scalars, so stepping any of them across a sweep reuses one
compiled executable; shape/structure fields remain the jit cache key.
These tests pin the contract down:

* the split itself (dtypes, static gate derivation),
* a K-step numeric sweep compiles exactly once (cache-size delta AND the
  engine/compiles / engine/cache_hits registry counters),
* dynamic-knob results are bit-identical to fresh-compile runs,
* shape knobs still recompile (the gates work both ways),
* the persistent compilation cache round-trips executables through disk,
* the CLI flag plumbs through.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.engine import (EngineKnobs, EngineParams, EngineStatic,
                                   clear_compile_cache, compiled_cache_size,
                                   init_state, make_cluster_tables,
                                   run_rounds)
from gossip_sim_tpu.obs import get_registry


def _cluster(n=96, seed=11):
    rng = np.random.default_rng(seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    return make_cluster_tables(stakes)


def _fresh(params, tables, origins, rounds, key=3, **kw):
    state = init_state(jax.random.PRNGKey(key), tables, origins, params)
    state, rows = run_rounds(params, tables, origins, state, rounds, **kw)
    return state, jax.tree_util.tree_map(np.asarray, rows)


# --------------------------------------------------------------------------
# the split
# --------------------------------------------------------------------------

class TestSplit:
    def test_split_partitions_every_field(self):
        """No EngineParams field may fall through the split: each one must
        land in the static tuple or the knob pytree (a new field that does
        neither would silently stop affecting the compiled engine)."""
        static_fields = set(EngineStatic._fields) - {
            "has_fail", "has_loss", "has_churn", "has_partition"}
        knob_fields = set(EngineKnobs._fields)
        assert static_fields | knob_fields == set(EngineParams._fields)
        assert not static_fields & knob_fields

    def test_knob_dtypes_fixed(self):
        _, kn = EngineParams(num_nodes=10).split()
        assert kn.probability_of_rotation.dtype == np.float32
        for f in ("prune_stake_threshold", "fail_fraction",
                  "packet_loss_rate", "churn_fail_rate",
                  "churn_recover_rate"):
            assert getattr(kn, f).dtype == np.float64, f
        for f in ("min_ingress_nodes", "warm_up_rounds", "fail_at",
                  "partition_at", "heal_at"):
            assert getattr(kn, f).dtype == np.int32, f
        assert kn.impair_seed.dtype == np.uint32

    def test_static_gates_derive_from_knobs(self):
        base = EngineParams(num_nodes=10)
        st, _ = base.split()
        assert not (st.has_fail or st.has_loss or st.has_churn
                    or st.has_partition or st.has_impairments)
        assert base._replace(packet_loss_rate=0.1).split()[0].has_loss
        assert base._replace(churn_recover_rate=0.2).split()[0].has_churn
        assert base._replace(partition_at=3).split()[0].has_partition
        st_f = base._replace(fail_at=2, fail_fraction=0.1).split()[0]
        assert st_f.has_fail and not st_f.has_impairments
        # fail needs both the schedule and a nonzero fraction
        assert not base._replace(fail_at=2).split()[0].has_fail

    def test_numeric_steps_share_one_static_key(self):
        base = EngineParams(num_nodes=10, packet_loss_rate=0.1)
        stepped = base._replace(packet_loss_rate=0.3,
                                probability_of_rotation=0.5,
                                prune_stake_threshold=0.4,
                                min_ingress_nodes=5, warm_up_rounds=7,
                                impair_seed=99)
        assert base.static_part() == stepped.static_part()
        assert base._replace(push_fanout=9).static_part() != \
            base.static_part()

    def test_derived_properties_match_facade(self):
        p = EngineParams(num_nodes=100, push_fanout=10, inbound_cap=0,
                         trace_prune_cap=0)
        st = p.static_part()
        assert st.k_inbound == p.k_inbound == 20
        assert st.prune_cap == p.prune_cap == 1600
        assert st.num_buckets == p.num_buckets


# --------------------------------------------------------------------------
# recompile-count regression guard
# --------------------------------------------------------------------------

class TestCompileOnce:
    N = 96
    ROUNDS = 5

    def test_four_step_numeric_sweep_compiles_exactly_once(self):
        """The ISSUE-4 acceptance check: a 4-step sweep over a numeric
        (non-shape) knob builds one executable, and the span registry
        counts 1 compile + 3 cache hits for it."""
        tables = _cluster(self.N)
        origins = jnp.arange(2, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            packet_loss_rate=0.05, impair_seed=5)
        reg = get_registry()
        clear_compile_cache()
        before = compiled_cache_size()
        c0 = reg.counter("engine/compiles")
        h0 = reg.counter("engine/cache_hits")
        for k in range(4):
            _fresh(base._replace(packet_loss_rate=0.05 + 0.05 * k),
                   tables, origins, self.ROUNDS)
        assert compiled_cache_size() - before == 1
        assert reg.counter("engine/compiles") - c0 == 1
        assert reg.counter("engine/cache_hits") - h0 == 3

    def test_every_knob_field_is_dynamic(self):
        """Stepping EVERY EngineKnobs field at once (within the same gate
        configuration) must not recompile."""
        tables = _cluster(self.N)
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=2,
                            packet_loss_rate=0.1, churn_fail_rate=0.01,
                            churn_recover_rate=0.2, partition_at=1,
                            heal_at=3, fail_at=1, fail_fraction=0.05,
                            impair_seed=1)
        _fresh(base, tables, origins, self.ROUNDS)
        before = compiled_cache_size()
        stepped = base._replace(
            probability_of_rotation=0.2, prune_stake_threshold=0.33,
            min_ingress_nodes=4, warm_up_rounds=3, fail_at=2,
            fail_fraction=0.21, packet_loss_rate=0.17, churn_fail_rate=0.03,
            churn_recover_rate=0.4, partition_at=2, heal_at=4,
            impair_seed=1234)
        _fresh(stepped, tables, origins, self.ROUNDS)
        assert compiled_cache_size() == before

    def test_shape_knobs_still_recompile(self):
        tables = _cluster(self.N)
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0)
        _fresh(base, tables, origins, self.ROUNDS)
        before = compiled_cache_size()
        _fresh(base._replace(push_fanout=8), tables, origins, self.ROUNDS)
        assert compiled_cache_size() == before + 1
        # crossing an impairment on/off boundary flips a static gate: one
        # more compile, after which stepping the rate is free again
        _fresh(base._replace(packet_loss_rate=0.2), tables, origins,
               self.ROUNDS)
        assert compiled_cache_size() == before + 2
        _fresh(base._replace(packet_loss_rate=0.4), tables, origins,
               self.ROUNDS)
        assert compiled_cache_size() == before + 2

    def test_dynamic_knob_results_bit_identical_to_fresh_compile(self):
        """A knob value run against a warm executable (compiled for a
        DIFFERENT value) must produce bit-identical rows and state to a
        fresh compile of that very value."""
        tables = _cluster(self.N)
        origins = jnp.arange(2, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            packet_loss_rate=0.25, churn_fail_rate=0.02,
                            churn_recover_rate=0.3, partition_at=1,
                            heal_at=4, impair_seed=9)
        target = base._replace(packet_loss_rate=0.12,
                               probability_of_rotation=0.05,
                               prune_stake_threshold=0.2, impair_seed=21)
        _fresh(base, tables, origins, self.ROUNDS, detail=True)  # carrier
        before = compiled_cache_size()
        s_warm, r_warm = _fresh(target, tables, origins, self.ROUNDS,
                                detail=True)
        assert compiled_cache_size() == before, "knob step recompiled"
        clear_compile_cache()
        s_cold, r_cold = _fresh(target, tables, origins, self.ROUNDS,
                                detail=True)
        for k in r_cold:
            np.testing.assert_array_equal(r_warm[k], r_cold[k], err_msg=k)
        for f in s_cold._fields:
            np.testing.assert_array_equal(np.asarray(getattr(s_warm, f)),
                                          np.asarray(getattr(s_cold, f)),
                                          err_msg=f)


# --------------------------------------------------------------------------
# persistent compilation cache
# --------------------------------------------------------------------------

def test_persistent_cache_round_trips_executables(tmp_path):
    """Enabling the cache writes executables to disk on compile (misses)
    and serves an identical program from disk after the in-memory cache is
    dropped (hits)."""
    import jax as _jax

    from gossip_sim_tpu.engine import (enable_persistent_cache,
                                       persistent_cache_counters)

    cc = str(tmp_path / "cc")
    try:
        assert enable_persistent_cache(cc) == cc
        tables = _cluster(48)
        origins = jnp.arange(1, dtype=jnp.int32)
        params = EngineParams(num_nodes=48, warm_up_rounds=0,
                              probability_of_rotation=0.9)
        clear_compile_cache()
        c0 = persistent_cache_counters()
        _, rows1 = _fresh(params, tables, origins, 3)
        c1 = persistent_cache_counters()
        assert c1["misses"] > c0["misses"]
        assert os.listdir(cc), "no cache entries written"
        # drop the in-memory executable; the disk cache must serve it
        clear_compile_cache()
        _, rows2 = _fresh(params, tables, origins, 3)
        c2 = persistent_cache_counters()
        assert c2["hits"] > c1["hits"]
        for k in rows1:
            np.testing.assert_array_equal(rows1[k], rows2[k], err_msg=k)
    finally:
        # leave no process-wide cache state behind for later tests
        _jax.config.update("jax_compilation_cache_dir", None)
        from gossip_sim_tpu.engine import cache as _cache_mod
        _cache_mod._enabled_dir = None


def test_cli_compilation_cache_flag_plumbs_through(tmp_path):
    from gossip_sim_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--compilation-cache-dir", str(tmp_path)])
    cfg = config_from_args(args)
    assert cfg.compilation_cache_dir == str(tmp_path)
    assert config_from_args(
        build_parser().parse_args([])).compilation_cache_dir == ""


def test_run_report_carries_compile_accounting(tmp_path):
    """--run-report surfaces compiles/cache_hits flat keys and the
    compilation_cache section (schema-valid)."""
    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.obs.report import validate_run_report
    import json

    report_path = str(tmp_path / "report.json")
    rc = cli_main(["--num-synthetic-nodes", "40", "--iterations", "4",
                   "--warm-up-rounds", "2", "--backend", "tpu",
                   "--run-report", report_path])
    assert rc == 0
    with open(report_path) as f:
        report = json.load(f)
    assert validate_run_report(report) == []
    assert report["compiles"] >= 1
    assert report["counters"]["engine/compiles"] >= 1
    assert set(report["compilation_cache"]) == {"dir", "hits", "misses"}


# --------------------------------------------------------------------------
# knobs override argument
# --------------------------------------------------------------------------

def test_run_rounds_explicit_knobs_override():
    """run_rounds(knobs=...) overrides the values embedded in params —
    the hook sweeps use to step a knob without rebuilding EngineParams."""
    tables = _cluster(48)
    origins = jnp.arange(1, dtype=jnp.int32)
    loud = EngineParams(num_nodes=48, warm_up_rounds=0,
                        probability_of_rotation=1.0)
    quiet = loud._replace(probability_of_rotation=0.0)
    _, r_override = _fresh(loud, tables, origins, 4,
                           knobs=quiet.knob_values())
    _, r_quiet = _fresh(quiet, tables, origins, 4)
    for k in r_quiet:
        np.testing.assert_array_equal(r_override[k], r_quiet[k], err_msg=k)


def test_explicit_knobs_gate_mismatch_raises():
    """A knob override activating an impairment the compile key gates OUT
    would be silently ignored by the compiled graph; the boundary must
    reject it instead of simulating wrong physics."""
    tables = _cluster(48)
    origins = jnp.arange(1, dtype=jnp.int32)
    lossless = EngineParams(num_nodes=48, warm_up_rounds=0)
    lossy_knobs = lossless._replace(packet_loss_rate=0.3).knob_values()
    state = init_state(jax.random.PRNGKey(0), tables, origins, lossless)
    with pytest.raises(ValueError, match="has_loss"):
        run_rounds(lossless, tables, origins, state, 2, knobs=lossy_knobs)


def test_zero_knobs_against_gated_graph_bit_identical_to_unimpaired():
    """The safe direction is allowed and exact: off/zero knob values run
    through a fully impairment-gated graph must reproduce the unimpaired
    engine bit-for-bit (a knobs= sweep can include its 0 endpoint without
    recompiling) — including partition_at = -1, whose off endpoint the
    traced window test must honor."""
    tables = _cluster(48)
    origins = jnp.arange(2, dtype=jnp.int32)
    gated = EngineParams(num_nodes=48, warm_up_rounds=0,
                         packet_loss_rate=0.2, churn_fail_rate=0.05,
                         churn_recover_rate=0.3, partition_at=1, heal_at=3,
                         impair_seed=4)
    off = gated._replace(packet_loss_rate=0.0, churn_fail_rate=0.0,
                         churn_recover_rate=0.0, partition_at=-1,
                         heal_at=-1)
    plain = EngineParams(num_nodes=48, warm_up_rounds=0)
    assert gated.static_part().has_impairments
    _, r_off = _fresh(gated, tables, origins, 6, knobs=off.knob_values())
    _, r_plain = _fresh(plain, tables, origins, 6)
    for k in r_plain:
        np.testing.assert_array_equal(r_off[k], r_plain[k], err_msg=k)


def test_round_step_static_requires_knobs():
    tables = _cluster(48)
    origins = jnp.arange(1, dtype=jnp.int32)
    params = EngineParams(num_nodes=48, warm_up_rounds=0)
    state = init_state(jax.random.PRNGKey(0), tables, origins, params)
    from gossip_sim_tpu.engine import round_step
    with pytest.raises(TypeError, match="knobs"):
        round_step(params.static_part(), tables, origins, state,
                   jnp.int32(0))
