"""Compile-once sweep regression tests (ISSUE 4) + sweep lanes (ISSUE 6).

The EngineParams split (engine/params.py): numeric knobs are traced
EngineKnobs scalars, so stepping any of them across a sweep reuses one
compiled executable; shape/structure fields remain the jit cache key.
These tests pin the contract down:

* the split itself (dtypes, static gate derivation),
* a K-step numeric sweep compiles exactly once (cache-size delta AND the
  engine/compiles / engine/cache_hits registry counters),
* dynamic-knob results are bit-identical to fresh-compile runs,
* shape knobs still recompile (the gates work both ways),
* the persistent compilation cache round-trips executables through disk,
* the CLI flag plumbs through.

Sweep lanes (engine/lanes.py, ISSUE 6) extend the contract: K knob
vectors stacked on a vmapped lane axis run as ONE batched device program,
bit-identical per lane to serial runs — including a 1-lane batch vs the
serial path, lanes whose convergence behavior differs wildly, and a lane
count that doesn't divide the sweep (tail padding must never leak into
stats or Influx).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.engine import (EngineKnobs, EngineParams, EngineStatic,
                                   broadcast_state, clear_compile_cache,
                                   clear_lane_cache, compiled_cache_size,
                                   init_state, lane_cache_size, lane_state,
                                   make_cluster_tables, merge_lane_statics,
                                   run_rounds, run_rounds_lanes, stack_knobs)
from gossip_sim_tpu.obs import get_registry


def _cluster(n=96, seed=11):
    rng = np.random.default_rng(seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    return make_cluster_tables(stakes)


def _fresh(params, tables, origins, rounds, key=3, **kw):
    state = init_state(jax.random.PRNGKey(key), tables, origins, params)
    state, rows = run_rounds(params, tables, origins, state, rounds, **kw)
    return state, jax.tree_util.tree_map(np.asarray, rows)


# --------------------------------------------------------------------------
# the split
# --------------------------------------------------------------------------

class TestSplit:
    def test_split_partitions_every_field(self):
        """No EngineParams field may fall through the split: each one must
        land in the static tuple or the knob pytree (a new field that does
        neither would silently stop affecting the compiled engine)."""
        # derived statics carry no same-named params field: the coarse
        # impairment gates, and traffic_slots (resolved from
        # traffic_values + the queue caps, engine/params.py)
        static_fields = set(EngineStatic._fields) - {
            "has_fail", "has_loss", "has_churn", "has_partition",
            "traffic_slots"}
        knob_fields = set(EngineKnobs._fields)
        assert (static_fields | knob_fields | {"traffic_values"}
                == set(EngineParams._fields))
        assert not static_fields & knob_fields

    def test_knob_dtypes_fixed(self):
        _, kn = EngineParams(num_nodes=10).split()
        assert kn.probability_of_rotation.dtype == np.float32
        for f in ("prune_stake_threshold", "fail_fraction",
                  "packet_loss_rate", "churn_fail_rate",
                  "churn_recover_rate"):
            assert getattr(kn, f).dtype == np.float64, f
        for f in ("min_ingress_nodes", "warm_up_rounds", "fail_at",
                  "partition_at", "heal_at"):
            assert getattr(kn, f).dtype == np.int32, f
        assert kn.impair_seed.dtype == np.uint32

    def test_static_gates_derive_from_knobs(self):
        base = EngineParams(num_nodes=10)
        st, _ = base.split()
        assert not (st.has_fail or st.has_loss or st.has_churn
                    or st.has_partition or st.has_impairments)
        assert base._replace(packet_loss_rate=0.1).split()[0].has_loss
        assert base._replace(churn_recover_rate=0.2).split()[0].has_churn
        assert base._replace(partition_at=3).split()[0].has_partition
        st_f = base._replace(fail_at=2, fail_fraction=0.1).split()[0]
        assert st_f.has_fail and not st_f.has_impairments
        # fail needs both the schedule and a nonzero fraction
        assert not base._replace(fail_at=2).split()[0].has_fail

    def test_numeric_steps_share_one_static_key(self):
        base = EngineParams(num_nodes=10, packet_loss_rate=0.1)
        stepped = base._replace(packet_loss_rate=0.3,
                                probability_of_rotation=0.5,
                                prune_stake_threshold=0.4,
                                min_ingress_nodes=5, warm_up_rounds=7,
                                impair_seed=99)
        assert base.static_part() == stepped.static_part()
        assert base._replace(push_fanout=9).static_part() != \
            base.static_part()

    def test_derived_properties_match_facade(self):
        p = EngineParams(num_nodes=100, push_fanout=10, inbound_cap=0,
                         trace_prune_cap=0)
        st = p.static_part()
        assert st.k_inbound == p.k_inbound == 20
        assert st.prune_cap == p.prune_cap == 1600
        assert st.num_buckets == p.num_buckets


# --------------------------------------------------------------------------
# recompile-count regression guard
# --------------------------------------------------------------------------

class TestCompileOnce:
    N = 96
    ROUNDS = 5

    def test_four_step_numeric_sweep_compiles_exactly_once(self):
        """The ISSUE-4 acceptance check: a 4-step sweep over a numeric
        (non-shape) knob builds one executable, and the span registry
        counts 1 compile + 3 cache hits for it."""
        tables = _cluster(self.N)
        origins = jnp.arange(2, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            packet_loss_rate=0.05, impair_seed=5)
        reg = get_registry()
        clear_compile_cache()
        before = compiled_cache_size()
        c0 = reg.counter("engine/compiles")
        h0 = reg.counter("engine/cache_hits")
        for k in range(4):
            _fresh(base._replace(packet_loss_rate=0.05 + 0.05 * k),
                   tables, origins, self.ROUNDS)
        assert compiled_cache_size() - before == 1
        assert reg.counter("engine/compiles") - c0 == 1
        assert reg.counter("engine/cache_hits") - h0 == 3

    def test_every_knob_field_is_dynamic(self):
        """Stepping EVERY EngineKnobs field at once (within the same gate
        configuration) must not recompile."""
        tables = _cluster(self.N)
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=2,
                            packet_loss_rate=0.1, churn_fail_rate=0.01,
                            churn_recover_rate=0.2, partition_at=1,
                            heal_at=3, fail_at=1, fail_fraction=0.05,
                            impair_seed=1)
        _fresh(base, tables, origins, self.ROUNDS)
        before = compiled_cache_size()
        stepped = base._replace(
            probability_of_rotation=0.2, prune_stake_threshold=0.33,
            min_ingress_nodes=4, warm_up_rounds=3, fail_at=2,
            fail_fraction=0.21, packet_loss_rate=0.17, churn_fail_rate=0.03,
            churn_recover_rate=0.4, partition_at=2, heal_at=4,
            impair_seed=1234)
        _fresh(stepped, tables, origins, self.ROUNDS)
        assert compiled_cache_size() == before

    def test_shape_knobs_still_recompile(self):
        tables = _cluster(self.N)
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0)
        _fresh(base, tables, origins, self.ROUNDS)
        before = compiled_cache_size()
        _fresh(base._replace(push_fanout=8), tables, origins, self.ROUNDS)
        assert compiled_cache_size() == before + 1
        # crossing an impairment on/off boundary flips a static gate: one
        # more compile, after which stepping the rate is free again
        _fresh(base._replace(packet_loss_rate=0.2), tables, origins,
               self.ROUNDS)
        assert compiled_cache_size() == before + 2
        _fresh(base._replace(packet_loss_rate=0.4), tables, origins,
               self.ROUNDS)
        assert compiled_cache_size() == before + 2

    def test_dynamic_knob_results_bit_identical_to_fresh_compile(self):
        """A knob value run against a warm executable (compiled for a
        DIFFERENT value) must produce bit-identical rows and state to a
        fresh compile of that very value."""
        tables = _cluster(self.N)
        origins = jnp.arange(2, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                            packet_loss_rate=0.25, churn_fail_rate=0.02,
                            churn_recover_rate=0.3, partition_at=1,
                            heal_at=4, impair_seed=9)
        target = base._replace(packet_loss_rate=0.12,
                               probability_of_rotation=0.05,
                               prune_stake_threshold=0.2, impair_seed=21)
        _fresh(base, tables, origins, self.ROUNDS, detail=True)  # carrier
        before = compiled_cache_size()
        s_warm, r_warm = _fresh(target, tables, origins, self.ROUNDS,
                                detail=True)
        assert compiled_cache_size() == before, "knob step recompiled"
        clear_compile_cache()
        s_cold, r_cold = _fresh(target, tables, origins, self.ROUNDS,
                                detail=True)
        for k in r_cold:
            np.testing.assert_array_equal(r_warm[k], r_cold[k], err_msg=k)
        for f in s_cold._fields:
            np.testing.assert_array_equal(np.asarray(getattr(s_warm, f)),
                                          np.asarray(getattr(s_cold, f)),
                                          err_msg=f)


# --------------------------------------------------------------------------
# persistent compilation cache
# --------------------------------------------------------------------------

def test_persistent_cache_round_trips_executables(tmp_path):
    """Enabling the cache writes executables to disk on compile (misses)
    and serves an identical program from disk after the in-memory cache is
    dropped (hits)."""
    import jax as _jax

    from gossip_sim_tpu.engine import (enable_persistent_cache,
                                       persistent_cache_counters)

    cc = str(tmp_path / "cc")
    try:
        assert enable_persistent_cache(cc) == cc
        tables = _cluster(48)
        origins = jnp.arange(1, dtype=jnp.int32)
        params = EngineParams(num_nodes=48, warm_up_rounds=0,
                              probability_of_rotation=0.9)
        clear_compile_cache()
        c0 = persistent_cache_counters()
        _, rows1 = _fresh(params, tables, origins, 3)
        c1 = persistent_cache_counters()
        assert c1["misses"] > c0["misses"]
        assert os.listdir(cc), "no cache entries written"
        # drop the in-memory executable; the disk cache must serve it
        clear_compile_cache()
        _, rows2 = _fresh(params, tables, origins, 3)
        c2 = persistent_cache_counters()
        assert c2["hits"] > c1["hits"]
        for k in rows1:
            np.testing.assert_array_equal(rows1[k], rows2[k], err_msg=k)
    finally:
        # leave no process-wide cache state behind for later tests
        _jax.config.update("jax_compilation_cache_dir", None)
        from gossip_sim_tpu.engine import cache as _cache_mod
        _cache_mod._enabled_dir = None


def test_cli_compilation_cache_flag_plumbs_through(tmp_path):
    from gossip_sim_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--compilation-cache-dir", str(tmp_path)])
    cfg = config_from_args(args)
    assert cfg.compilation_cache_dir == str(tmp_path)
    assert config_from_args(
        build_parser().parse_args([])).compilation_cache_dir == ""


def test_run_report_carries_compile_accounting(tmp_path):
    """--run-report surfaces compiles/cache_hits flat keys and the
    compilation_cache section (schema-valid)."""
    from gossip_sim_tpu.cli import main as cli_main
    from gossip_sim_tpu.obs.report import validate_run_report
    import json

    report_path = str(tmp_path / "report.json")
    rc = cli_main(["--num-synthetic-nodes", "40", "--iterations", "4",
                   "--warm-up-rounds", "2", "--backend", "tpu",
                   "--run-report", report_path])
    assert rc == 0
    with open(report_path) as f:
        report = json.load(f)
    assert validate_run_report(report) == []
    assert report["compiles"] >= 1
    assert report["counters"]["engine/compiles"] >= 1
    assert set(report["compilation_cache"]) == {"dir", "hits", "misses"}


# --------------------------------------------------------------------------
# knobs override argument
# --------------------------------------------------------------------------

def test_run_rounds_explicit_knobs_override():
    """run_rounds(knobs=...) overrides the values embedded in params —
    the hook sweeps use to step a knob without rebuilding EngineParams."""
    tables = _cluster(48)
    origins = jnp.arange(1, dtype=jnp.int32)
    loud = EngineParams(num_nodes=48, warm_up_rounds=0,
                        probability_of_rotation=1.0)
    quiet = loud._replace(probability_of_rotation=0.0)
    _, r_override = _fresh(loud, tables, origins, 4,
                           knobs=quiet.knob_values())
    _, r_quiet = _fresh(quiet, tables, origins, 4)
    for k in r_quiet:
        np.testing.assert_array_equal(r_override[k], r_quiet[k], err_msg=k)


def test_explicit_knobs_gate_mismatch_raises():
    """A knob override activating an impairment the compile key gates OUT
    would be silently ignored by the compiled graph; the boundary must
    reject it instead of simulating wrong physics."""
    tables = _cluster(48)
    origins = jnp.arange(1, dtype=jnp.int32)
    lossless = EngineParams(num_nodes=48, warm_up_rounds=0)
    lossy_knobs = lossless._replace(packet_loss_rate=0.3).knob_values()
    state = init_state(jax.random.PRNGKey(0), tables, origins, lossless)
    with pytest.raises(ValueError, match="has_loss"):
        run_rounds(lossless, tables, origins, state, 2, knobs=lossy_knobs)


def test_zero_knobs_against_gated_graph_bit_identical_to_unimpaired():
    """The safe direction is allowed and exact: off/zero knob values run
    through a fully impairment-gated graph must reproduce the unimpaired
    engine bit-for-bit (a knobs= sweep can include its 0 endpoint without
    recompiling) — including partition_at = -1, whose off endpoint the
    traced window test must honor."""
    tables = _cluster(48)
    origins = jnp.arange(2, dtype=jnp.int32)
    gated = EngineParams(num_nodes=48, warm_up_rounds=0,
                         packet_loss_rate=0.2, churn_fail_rate=0.05,
                         churn_recover_rate=0.3, partition_at=1, heal_at=3,
                         impair_seed=4)
    off = gated._replace(packet_loss_rate=0.0, churn_fail_rate=0.0,
                         churn_recover_rate=0.0, partition_at=-1,
                         heal_at=-1)
    plain = EngineParams(num_nodes=48, warm_up_rounds=0)
    assert gated.static_part().has_impairments
    _, r_off = _fresh(gated, tables, origins, 6, knobs=off.knob_values())
    _, r_plain = _fresh(plain, tables, origins, 6)
    for k in r_plain:
        np.testing.assert_array_equal(r_off[k], r_plain[k], err_msg=k)


def test_round_step_static_requires_knobs():
    tables = _cluster(48)
    origins = jnp.arange(1, dtype=jnp.int32)
    params = EngineParams(num_nodes=48, warm_up_rounds=0)
    state = init_state(jax.random.PRNGKey(0), tables, origins, params)
    from gossip_sim_tpu.engine import round_step
    with pytest.raises(TypeError, match="knobs"):
        round_step(params.static_part(), tables, origins, state,
                   jnp.int32(0))


# --------------------------------------------------------------------------
# device-resident sweep lanes (engine/lanes.py, ISSUE 6)
# --------------------------------------------------------------------------

def _assert_rows_equal(a, b, msg=""):
    assert set(a) == set(b), msg
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"{msg}:{k}")


def _assert_state_equal(a, b, msg=""):
    for f in a._fields:
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)),
                                      err_msg=f"{msg}:{f}")


class TestMergeLaneStatics:
    def test_gate_union_and_pull_slots_max(self):
        base = EngineParams(num_nodes=32)
        statics = [
            base.static_part(),
            base._replace(packet_loss_rate=0.2).static_part(),
            base._replace(churn_fail_rate=0.1).static_part(),
            base._replace(gossip_mode="push").static_part(),
        ]
        merged = merge_lane_statics(statics)
        assert merged.has_loss and merged.has_churn
        assert not merged.has_fail and not merged.has_partition
        pulls = [base._replace(gossip_mode="push-pull", pull_fanout=f)
                 .static_part() for f in (2, 6, 12)]
        assert merge_lane_statics(pulls).pull_slots == 12

    def test_shape_divergence_raises(self):
        base = EngineParams(num_nodes=32)
        with pytest.raises(ValueError, match="push_fanout"):
            merge_lane_statics([base.static_part(),
                                base._replace(push_fanout=9).static_part()])
        with pytest.raises(ValueError, match="gossip_mode"):
            merge_lane_statics(
                [base.static_part(),
                 base._replace(gossip_mode="push-pull").static_part()])


class TestSweepLanes:
    N = 96
    ROUNDS = 6

    def _serial(self, static, knobs, tables, origins, rounds, seed=3):
        """One serial reference run (own init, warm jit or not — results
        are value-equal either way per the PR-4 contract)."""
        params0 = EngineParams(num_nodes=self.N, warm_up_rounds=0)
        state = init_state(jax.random.PRNGKey(seed), tables, origins,
                           params0)
        state, rows = run_rounds(static, tables, origins, state, rounds,
                                 detail=True, knobs=knobs)
        return (jax.tree_util.tree_map(np.asarray, state),
                jax.tree_util.tree_map(np.asarray, rows))

    def test_single_lane_bit_identical_to_serial(self):
        """K=1: a lane batch of one is the serial path, bit for bit."""
        tables = _cluster(self.N)
        origins = jnp.arange(2, dtype=jnp.int32)
        params = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                              packet_loss_rate=0.15, impair_seed=5)
        static, kn = params.split()
        base = init_state(jax.random.PRNGKey(3), tables, origins, params)
        states, lrows = run_rounds_lanes(static, tables, origins,
                                         broadcast_state(base, 1),
                                         stack_knobs([kn]), self.ROUNDS,
                                         detail=True)
        lrows = jax.tree_util.tree_map(np.asarray, lrows)
        s_state, s_rows = self._serial(static, kn, tables, origins,
                                       self.ROUNDS)
        _assert_rows_equal({k: v[:, 0] for k, v in lrows.items()}, s_rows,
                           "K=1 rows")
        _assert_state_equal(lane_state(states, 0), s_state, "K=1 state")

    def test_divergent_convergence_lanes_match_serial(self):
        """Lanes with wildly different convergence (lossless vs 60% loss
        vs heavy churn) share one batched scan; the no-op masking of
        converged lanes must keep every lane bit-identical to its serial
        run — including lanes whose own static would gate the impairment
        blocks out entirely."""
        tables = _cluster(self.N)
        origins = jnp.arange(1, dtype=jnp.int32)
        base = EngineParams(num_nodes=self.N, warm_up_rounds=2,
                            impair_seed=9)
        lanes = [
            base,                                     # clean, fast converge
            base._replace(packet_loss_rate=0.6),      # heavy loss, slow
            base._replace(churn_fail_rate=0.2,
                          churn_recover_rate=0.05),   # churning
            base._replace(packet_loss_rate=0.3,
                          churn_fail_rate=0.05,
                          churn_recover_rate=0.5),
        ]
        static = merge_lane_statics([p.static_part() for p in lanes])
        knob_list = [p.knob_values() for p in lanes]
        st0 = init_state(jax.random.PRNGKey(3), tables, origins, lanes[0])
        states, lrows = run_rounds_lanes(static, tables, origins,
                                         broadcast_state(st0, len(lanes)),
                                         stack_knobs(knob_list),
                                         self.ROUNDS, detail=True)
        lrows = jax.tree_util.tree_map(np.asarray, lrows)
        for i, kn in enumerate(knob_list):
            s_state, s_rows = self._serial(static, kn, tables, origins,
                                           self.ROUNDS)
            _assert_rows_equal({k: v[:, i] for k, v in lrows.items()},
                               s_rows, f"lane{i} rows")
            _assert_state_equal(lane_state(states, i), s_state,
                                f"lane{i} state")

    def test_lane_batch_compiles_once(self):
        tables = _cluster(self.N)
        origins = jnp.arange(1, dtype=jnp.int32)
        params = EngineParams(num_nodes=self.N, warm_up_rounds=0,
                              packet_loss_rate=0.1)
        static, _ = params.split()
        knob_list = [params._replace(packet_loss_rate=0.1 * k).knob_values()
                     for k in range(4)]
        base = init_state(jax.random.PRNGKey(3), tables, origins, params)
        reg = get_registry()
        clear_lane_cache()
        before = lane_cache_size()
        c0 = reg.counter("engine/compiles")
        h0 = reg.counter("engine/cache_hits")
        for _ in range(3):   # 3 lane batches, one executable
            run_rounds_lanes(static, tables, origins,
                             broadcast_state(base, 4),
                             stack_knobs(knob_list), 3)
        assert lane_cache_size() - before == 1
        assert reg.counter("engine/compiles") - c0 == 1
        assert reg.counter("engine/cache_hits") - h0 == 2


# --------------------------------------------------------------------------
# --sweep-lanes CLI path (cli.run_lane_sweep)
# --------------------------------------------------------------------------

def _lane_cli_config(**kw):
    from gossip_sim_tpu.config import Config, StepSize, Testing
    base = dict(num_synthetic_nodes=64, gossip_iterations=7,
                warm_up_rounds=3, test_type=Testing.PACKET_LOSS,
                num_simulations=5, step_size=StepSize.parse("0.1"),
                packet_loss_rate=0.0, seed=13)
    base.update(kw)
    return Config(**base)


def _run_lane_dispatch(config, ranks=(1,)):
    from gossip_sim_tpu.cli import dispatch_sweeps
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection
    reset_unique_pubkeys()
    get_registry().reset()
    clear_compile_cache()
    clear_lane_cache()
    coll = GossipStatsCollection()
    coll.set_number_of_simulations(config.num_simulations)
    dpq = DatapointQueue()
    dispatch_sweeps(config, "", list(ranks), coll, dpq, "0")
    return coll, dpq.drain_deterministic_lines()


def _run_serial_reference(config, ranks=(1,)):
    """The serial arm of the lane contract: each sweep point as its own
    run_simulation against an identical cluster (counter reset per sim,
    the methodology test_origin_rank_sweep_batched_matches_serial set)."""
    from gossip_sim_tpu.cli import _stepped_sweep_config, run_simulation
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    from gossip_sim_tpu.sinks import DatapointQueue
    from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection
    coll = GossipStatsCollection()
    coll.set_number_of_simulations(config.num_simulations)
    dpq = DatapointQueue()
    for i in range(config.num_simulations):
        reset_unique_pubkeys()
        c, start = _stepped_sweep_config(config, i, list(ranks))
        run_simulation(c, "", coll, dpq, i, "0", start)
    return coll, dpq.drain_deterministic_lines()


def _assert_collections_equal(serial, lane):
    """Per-sim bit-exactness via the one canonical parity surface
    (GossipStats.parity_snapshot — shared with tools/lane_smoke.py)."""
    assert len(serial.collection) == len(lane.collection)
    for i, (a, b) in enumerate(zip(serial.collection, lane.collection)):
        sa, sb = a.parity_snapshot(), b.parity_snapshot()
        for key in sa:
            assert sa[key] == sb[key], f"sim{i}:{key}"


@pytest.mark.slow  # tier-1 budget; tools/lane_smoke gate covers this
def test_lane_sweep_tail_padding_never_leaks():
    """5 sims through 2 lanes = 3 batches, the last one half-padded: the
    padded lane's rows must never reach stats or Influx, every sim's
    stats must be bit-identical to its serial run, and the whole sweep
    must compile exactly one executable."""
    serial_coll, serial_pts = _run_serial_reference(_lane_cli_config())
    lane_coll, lane_pts = _run_lane_dispatch(_lane_cli_config(sweep_lanes=2))
    assert len(lane_coll.collection) == 5
    _assert_collections_equal(serial_coll, lane_coll)
    assert get_registry().counter("engine/compiles") == 1
    assert serial_pts == lane_pts
    # nothing in the wire payload mentions a sixth (padded) simulation
    assert not any("simulation_iter=5" in ln for ln in lane_pts)


@pytest.mark.slow  # tier-1 budget; tools/lane_smoke gate covers this
def test_lane_sweep_influx_and_stats_parity_churn_and_pull():
    """The acceptance sweeps beyond packet loss: churn and pull-fanout
    lane sweeps produce bit-identical per-sim stats and Influx payloads
    to the serial compile-once sweep."""
    from gossip_sim_tpu.config import StepSize, Testing
    for kw in (dict(test_type=Testing.CHURN,
                    step_size=StepSize.parse("0.05"),
                    churn_fail_rate=0.0, churn_recover_rate=0.3,
                    num_simulations=3),
               dict(test_type=Testing.PULL_FANOUT,
                    step_size=StepSize.parse("2"),
                    gossip_mode="push-pull", pull_fanout=1,
                    num_simulations=3)):
        serial_coll, serial_pts = _run_serial_reference(_lane_cli_config(**kw))
        lane_coll, lane_pts = _run_lane_dispatch(
            _lane_cli_config(sweep_lanes=3, **kw))
        _assert_collections_equal(serial_coll, lane_coll)
        assert serial_pts == lane_pts
        assert get_registry().counter("engine/compiles") == 1


def test_lane_sweep_rejects_trace_but_journals_checkpoints(tmp_path):
    """--trace-dir stays rejected in lane mode; --checkpoint-path is now
    REAL support (ISSUE 7 lifted guard_lane_checkpoint): a lane sweep
    writes a per-batch run journal instead of erroring out."""
    with pytest.raises(SystemExit, match="trace-dir"):
        _run_lane_dispatch(_lane_cli_config(sweep_lanes=2,
                                            trace_dir="/tmp/nope"))
    ck = str(tmp_path / "lane.npz")
    coll, _ = _run_lane_dispatch(_lane_cli_config(sweep_lanes=2,
                                                  checkpoint_path=ck))
    assert len(coll.collection) == 5
    import json
    from gossip_sim_tpu.resilience import journal_path
    lines = open(journal_path(ck)).read().splitlines()
    # header + one committed unit per lane batch (5 sims at 2 lanes = 3)
    assert len(lines) == 1 + 3
    assert [json.loads(ln)["unit"] for ln in lines[1:]] == [0, 1, 2]


def test_lane_sweep_falls_back_serially_for_shape_sweeps(caplog):
    """A static-shape sweep (push-fanout) with --sweep-lanes warns and
    runs the serial loop instead of erroring out."""
    import logging
    from gossip_sim_tpu.config import StepSize, Testing
    cfg = _lane_cli_config(test_type=Testing.PUSH_FANOUT,
                           step_size=StepSize.parse("1"),
                           num_simulations=2, sweep_lanes=2,
                           gossip_iterations=5, warm_up_rounds=3)
    with caplog.at_level(logging.WARNING):
        coll, _ = _run_lane_dispatch(cfg)
    assert len(coll.collection) == 2
    assert any("--sweep-lanes" in r.message for r in caplog.records)


def test_lane_sweep_no_measured_rounds_falls_back_serially(caplog):
    """iterations <= warm-up-rounds has nothing to lane-batch; the serial
    loop owns the degenerate behavior (preamble Influx points, warm-up-
    only sims), so the dispatcher must route there, not approximate it."""
    import logging
    cfg = _lane_cli_config(sweep_lanes=2, gossip_iterations=3,
                           warm_up_rounds=3, num_simulations=2)
    with caplog.at_level(logging.WARNING):
        coll, pts = _run_lane_dispatch(cfg)
    assert coll.is_empty()
    assert any("no measured rounds" in r.message for r in caplog.records)
    # the serial degenerate path still emits its per-sim Influx preamble
    assert any(ln.startswith("simulation_config") for ln in pts)


def test_cli_sweep_lanes_flag_plumbs_through():
    from gossip_sim_tpu.cli import build_parser, config_from_args
    args = build_parser().parse_args(["--sweep-lanes", "8"])
    assert config_from_args(args).sweep_lanes == 8
    assert config_from_args(build_parser().parse_args([])).sweep_lanes == 0
    with pytest.raises(SystemExit):
        config_from_args(build_parser().parse_args(["--sweep-lanes", "-1"]))
