"""write-accounts binary tests (reference: write_accounts_main.rs:62-125)."""

from gossip_sim_tpu.identity import Pubkey, pubkey_new_unique
from gossip_sim_tpu.ingest import load_accounts_yaml
from gossip_sim_tpu.write_accounts import build_parser, write_accounts


def test_default_flags():
    args = build_parser().parse_args([])
    assert args.num_nodes == (1 << 64) - 1  # "all" (write_accounts_main.rs:34)
    assert not args.zero_stakes
    assert not args.filter_zero_staked_nodes


def test_write_and_reload_roundtrip(tmp_path):
    accounts = {pubkey_new_unique(): s for s in (10, 0, 30, 0, 50)}
    path = str(tmp_path / "accounts.yaml")
    selected = write_accounts(accounts, 3, path, zero_stakes_only=False)
    assert len(selected) == 3
    reloaded = load_accounts_yaml(path)
    assert {pk.to_string(): s for pk, s in reloaded.items()} == \
        {pk.to_string(): s for pk, s in selected.items()}
    assert all(isinstance(pk, Pubkey) for pk in reloaded)


def test_zero_stakes_only(tmp_path):
    accounts = {pubkey_new_unique(): s for s in (10, 0, 30, 0, 50)}
    path = str(tmp_path / "zero.yaml")
    selected = write_accounts(accounts, 10, path, zero_stakes_only=True)
    assert len(selected) == 2
    assert all(s == 0 for s in selected.values())
