"""Resilient-execution tests (gossip_sim_tpu/resilience.py, ISSUE 7):
journal atomicity + replay, kill-and-resume bit-exactness on every
multi-unit run path, the device-dispatch watchdog (retry / CPU fallback /
abort), and the resumable CLI exit code."""

import json
import os
import signal
import time

import numpy as np
import pytest

from gossip_sim_tpu import resilience
from gossip_sim_tpu.config import Config, StepSize, Testing
from gossip_sim_tpu.obs import get_registry
from gossip_sim_tpu.resilience import (RESUMABLE_EXIT_CODE, DispatchPolicy,
                                       DeviceDispatchError,
                                       DeviceTimeoutError, RunJournal,
                                       journal_path, restore_stats,
                                       snapshot_from_jsonable,
                                       snapshot_to_jsonable,
                                       stats_unit_payload, supervised_call)
from gossip_sim_tpu.sinks import DatapointQueue
from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    resilience.reset_shutdown()
    resilience.set_fault_hook(None)
    yield
    resilience.reset_shutdown()
    resilience.set_fault_hook(None)


def _fresh(num_sims=1):
    from gossip_sim_tpu.engine import clear_compile_cache, clear_lane_cache
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    reset_unique_pubkeys()
    get_registry().reset()
    resilience.reset_shutdown()
    clear_compile_cache()
    clear_lane_cache()
    coll = GossipStatsCollection()
    coll.set_number_of_simulations(num_sims)
    return coll, DatapointQueue()


def _sweep_cfg(**kw):
    base = dict(num_synthetic_nodes=48, gossip_iterations=6,
                warm_up_rounds=2, test_type=Testing.PACKET_LOSS,
                num_simulations=4, step_size=StepSize.parse("0.1"),
                packet_loss_rate=0.0, seed=13)
    base.update(kw)
    return Config(**base)


def _snaps(coll):
    return [s.parity_snapshot() for s in coll.collection]


def _assert_parity(snaps_a, snaps_b, lines_a, lines_b):
    assert len(snaps_a) == len(snaps_b)
    for i, (a, b) in enumerate(zip(snaps_a, snaps_b)):
        for k in a:
            assert a[k] == b[k], f"sim{i}:{k}"
    assert lines_a == lines_b


# --------------------------------------------------------------------------
# journal mechanics
# --------------------------------------------------------------------------

def test_journal_commit_load_roundtrip(tmp_path):
    jp = str(tmp_path / "run.journal")
    key = {"seed": 1, "kind": "serial-sweep"}
    j = RunJournal(jp, key)
    j.commit(0, {"x": 1})
    j.commit(1, {"y": [1.5, 2.5]})
    j.close()
    j2 = RunJournal(jp, key, resume=True)
    assert j2.committed_prefix() == 2
    assert j2.records[0] == {"x": 1}
    assert j2.records[1] == {"y": [1.5, 2.5]}


def test_journal_tolerates_partial_trailing_line(tmp_path, caplog):
    """A SIGKILL mid-append leaves a torn last line; the loader must drop
    exactly that unit and keep every earlier one."""
    import logging
    jp = str(tmp_path / "run.journal")
    key = {"seed": 1}
    j = RunJournal(jp, key)
    j.commit(0, {"ok": True})
    j.commit(1, {"ok": True})
    j.close()
    with open(jp, "a") as f:
        f.write('{"unit": 2, "payload": {"tor')   # torn mid-write
    with caplog.at_level(logging.WARNING):
        j2 = RunJournal(jp, key, resume=True)
    assert j2.committed_prefix() == 2
    assert any("partial" in r.message for r in caplog.records)
    # committing after the torn line keeps the journal loadable
    j2.commit(2, {"ok": True})
    j2.close()
    j3 = RunJournal(jp, key, resume=True)
    assert j3.committed_prefix() == 3


def test_journal_rejects_run_key_drift(tmp_path):
    jp = str(tmp_path / "run.journal")
    RunJournal(jp, {"seed": 1, "num_simulations": 4}).close()
    with pytest.raises(SystemExit, match="seed"):
        RunJournal(jp, {"seed": 2, "num_simulations": 4}, resume=True)


def test_journal_overwrites_without_resume(tmp_path, caplog):
    import logging
    jp = str(tmp_path / "run.journal")
    j = RunJournal(jp, {"seed": 1})
    j.commit(0, {})
    j.close()
    with caplog.at_level(logging.WARNING):
        j2 = RunJournal(jp, {"seed": 1})     # no resume flag: fresh run
    assert j2.committed_prefix() == 0
    assert any("overwriting" in r.message for r in caplog.records)


# --------------------------------------------------------------------------
# snapshot serialization + stats restoration
# --------------------------------------------------------------------------

@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_snapshot_json_roundtrip_and_restore():
    """A finished sim's parity snapshot must survive
    JSON-serialize -> JSON-parse -> restore_stats exactly — including
    pubkey-keyed dicts, the failed set, and big lamport stakes."""
    from gossip_sim_tpu.cli import run_simulation
    coll, dpq = _fresh()
    cfg = _sweep_cfg(num_simulations=1, packet_loss_rate=0.15)
    run_simulation(cfg, "", coll, dpq, 0, "0", 0.0)
    stats = coll.collection[0]
    snap = stats.parity_snapshot()

    payload = json.loads(json.dumps(stats_unit_payload(stats)))
    assert snapshot_from_jsonable(payload["snapshot"]) == {
        k: snap[k] for k in snap}

    # rebuild the same cluster for stakes
    from gossip_sim_tpu.cli import load_cluster_accounts
    from gossip_sim_tpu.identity import reset_unique_pubkeys
    reset_unique_pubkeys()
    accounts, _ = load_cluster_accounts(cfg, "")
    restored = restore_stats(payload, cfg, dict(accounts))
    rsnap = restored.parity_snapshot()
    for k in snap:
        assert rsnap[k] == snap[k], k
    # and the re-finalized means match the live object's
    from gossip_sim_tpu.cli import _build_final_stats
    _build_final_stats(cfg, restored, dict(accounts))
    assert restored.coverage_stats.mean == stats.coverage_stats.mean
    assert restored.rmr_stats.mean == stats.rmr_stats.mean
    ldh_a = stats.get_last_delivery_hop_stats()
    ldh_b = restored.get_last_delivery_hop_stats()
    assert ldh_a == ldh_b


# --------------------------------------------------------------------------
# kill-and-resume bit-exactness, per run path
# --------------------------------------------------------------------------

def _run_sweep(cfg, num_sims=4, kill_after=0):
    from gossip_sim_tpu.cli import dispatch_sweeps
    coll, dpq = _fresh(num_sims)
    if kill_after:
        # after _fresh: reset_shutdown() would wipe an earlier setting
        resilience.set_kill_after_units(kill_after)
    dispatch_sweeps(cfg, "", [1], coll, dpq, "0")
    return coll, dpq.drain_deterministic_lines()


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_serial_sweep_kill_and_resume_bit_exact(tmp_path):
    coll_a, lines_a = _run_sweep(_sweep_cfg())

    ck = str(tmp_path / "sweep.npz")
    with pytest.raises(resilience.ResumableInterrupt):
        _run_sweep(_sweep_cfg(checkpoint_path=ck), kill_after=2)
    assert os.path.exists(journal_path(ck))

    coll_c, lines_c = _run_sweep(_sweep_cfg(checkpoint_path=ck,
                                            resume_path=ck))
    _assert_parity(_snaps(coll_a), _snaps(coll_c), lines_a, lines_c)
    reg = get_registry()
    assert reg.counter("resilience/resumed_units") == 2
    assert reg.counter("resilience/committed_units") == 2  # sims 2, 3


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_lane_sweep_kill_and_resume_bit_exact(tmp_path):
    cfg = _sweep_cfg(num_simulations=5, sweep_lanes=2)
    coll_a, lines_a = _run_sweep(cfg, 5)

    ck = str(tmp_path / "lane.npz")
    with pytest.raises(resilience.ResumableInterrupt):
        _run_sweep(_sweep_cfg(num_simulations=5, sweep_lanes=2,
                              checkpoint_path=ck), 5,
                   kill_after=1)             # after lane batch 0 of 3

    coll_c, lines_c = _run_sweep(
        _sweep_cfg(num_simulations=5, sweep_lanes=2, checkpoint_path=ck,
                   resume_path=ck), 5)
    _assert_parity(_snaps(coll_a), _snaps(coll_c), lines_a, lines_c)
    # the resumed process recomputed batches 1-2 with ONE compile and
    # replayed batch 0 without touching the engine
    assert get_registry().counter("engine/compiles") == 1


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_all_origins_kill_and_resume_bit_exact(tmp_path):
    from gossip_sim_tpu.cli import run_all_origins

    def cfg(**kw):
        return Config(num_synthetic_nodes=40, gossip_iterations=5,
                      warm_up_rounds=2, all_origins=True, origin_batch=16,
                      seed=9, **kw)

    _fresh()
    dq = DatapointQueue()
    s_a = run_all_origins(cfg(), "", dq, "0")
    lines_a = dq.drain_deterministic_lines()

    ck = str(tmp_path / "ao.npz")
    _fresh()
    resilience.set_kill_after_units(1)       # after origin batch 0 of 3
    with pytest.raises(resilience.ResumableInterrupt):
        run_all_origins(cfg(checkpoint_path=ck), "", DatapointQueue(), "0")
    assert os.path.exists(journal_path(ck))
    assert os.path.exists(str(tmp_path / "ao.aggstate.npz"))

    _fresh()
    dq2 = DatapointQueue()
    s_c = run_all_origins(cfg(checkpoint_path=ck, resume_path=ck), "",
                          dq2, "0")
    lines_c = dq2.drain_deterministic_lines()
    for k in s_a:
        if k in ("elapsed_s", "origin_iters_per_sec", "stats"):
            continue
        assert s_a[k] == s_c[k], k
    assert lines_a == lines_c


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_all_origins_sidecar_ahead_of_journal_reconciles(tmp_path):
    """A kill between the sidecar save and the journal commit leaves the
    aggregate one batch ahead; resume must commit the missing record
    instead of re-folding the batch (which would double-count its
    origins)."""
    from gossip_sim_tpu.cli import run_all_origins

    def cfg(**kw):
        return Config(num_synthetic_nodes=40, gossip_iterations=5,
                      warm_up_rounds=2, all_origins=True, origin_batch=16,
                      seed=9, **kw)

    _fresh()
    s_a = run_all_origins(cfg(), "", None, "0")

    ck = str(tmp_path / "ao.npz")
    _fresh()
    resilience.set_kill_after_units(2)
    with pytest.raises(resilience.ResumableInterrupt):
        run_all_origins(cfg(checkpoint_path=ck), "", None, "0")
    # simulate the crash window: drop the journal's last record while the
    # sidecar keeps both batches folded
    jp = journal_path(ck)
    lines = open(jp).read().splitlines()
    open(jp, "w").write("\n".join(lines[:-1]) + "\n")

    _fresh()
    s_c = run_all_origins(cfg(checkpoint_path=ck, resume_path=ck), "",
                          None, "0")
    for k in s_a:
        if k in ("elapsed_s", "origin_iters_per_sec", "stats"):
            continue
        assert s_a[k] == s_c[k], k


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_origin_rank_sweep_kill_and_resume_bit_exact(tmp_path, monkeypatch):
    import gossip_sim_tpu.cli as cli

    monkeypatch.setattr(cli, "HARVEST_BLOCK", 2)   # several units per run

    def cfg(**kw):
        return Config(num_synthetic_nodes=40, gossip_iterations=8,
                      warm_up_rounds=2, test_type=Testing.ORIGIN_RANK,
                      num_simulations=3, step_size=StepSize.parse("1"),
                      seed=9, **kw)

    ranks = [1, 3, 5]

    def run(c, kill_after=0):
        coll, dpq = _fresh(3)
        if kill_after:
            resilience.set_kill_after_units(kill_after)
        cli.run_origin_rank_sweep(c, "", ranks, coll, dpq, "0")
        return coll, dpq.drain_deterministic_lines()

    coll_a, lines_a = run(cfg())
    ck = str(tmp_path / "orank.npz")
    with pytest.raises(resilience.ResumableInterrupt):
        run(cfg(checkpoint_path=ck), kill_after=2)  # after block 1 of 3
    # the v5 state npz carries the journal cross-reference
    from gossip_sim_tpu.checkpoint import load_state
    _, _, meta = load_state(ck)
    assert meta["resilience"]["committed_units"] == 2
    assert meta["resilience"]["journal"] == "orank.journal"

    coll_c, lines_c = run(cfg(checkpoint_path=ck, resume_path=ck))
    _assert_parity(_snaps(coll_a), _snaps(coll_c), lines_a, lines_c)


def test_sweep_without_journal_still_stops_on_shutdown():
    """SIGTERM without --checkpoint-path: the run still stops promptly —
    the in-flight sim aborts at its next harvest-block boundary (nothing
    to resume from, but it must not run on for hours)."""
    resilience.set_kill_after_units(0)
    coll, dpq = _fresh(4)
    from gossip_sim_tpu.cli import dispatch_sweeps
    resilience.request_shutdown()
    with pytest.raises(resilience.ResumableInterrupt):
        dispatch_sweeps(_sweep_cfg(), "", [1], coll, dpq, "0")
    # the aborted sim never finalized: nothing partial leaks out
    assert len(coll.collection) == 0


# --------------------------------------------------------------------------
# device-dispatch supervisor
# --------------------------------------------------------------------------

def test_supervised_call_retries_transient_errors():
    calls = []

    def attempt():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient XLA flake")
        return "ok"

    reg = get_registry()
    reg.reset()
    pol = DispatchPolicy(retries=2, backoff_s=0.001)
    assert supervised_call("t", attempt, pol) == "ok"
    assert len(calls) == 3
    assert reg.counter("resilience/device_failures") == 2


def test_supervised_call_does_not_retry_programming_errors():
    def attempt():
        raise ValueError("shape mismatch is a bug, not a flake")
    with pytest.raises(ValueError):
        supervised_call("t", attempt, DispatchPolicy(retries=3,
                                                     backoff_s=0.001))


def test_supervised_call_timeout_then_recovery():
    calls = []

    def attempt():
        calls.append(1)
        if len(calls) == 1:
            time.sleep(0.5)              # hung first dispatch
        return "ok"

    pol = DispatchPolicy(timeout_s=0.05, retries=1, backoff_s=0.001)
    assert supervised_call("t", attempt, pol) == "ok"
    assert len(calls) == 2


def test_supervised_call_cpu_fallback_and_abort():
    def attempt():
        raise RuntimeError("persistently broken device")

    reg = get_registry()
    reg.reset()
    pol = DispatchPolicy(retries=1, backoff_s=0.001,
                         on_failure="cpu-fallback")
    out = supervised_call("t", attempt, pol, cpu_fallback=lambda: "cpu")
    assert out == "cpu"
    assert reg.counter("resilience/fallback_units") == 1

    with pytest.raises(DeviceDispatchError, match="--resume"):
        supervised_call("t", attempt,
                        DispatchPolicy(retries=0, backoff_s=0.001,
                                       on_failure="abort"),
                        cpu_fallback=lambda: "cpu")


def test_timeout_error_is_transient():
    assert resilience._is_transient(DeviceTimeoutError("x"))
    assert resilience._is_transient(RuntimeError("x"))
    assert not resilience._is_transient(TypeError("x"))
    # RuntimeError subclasses that are programming errors, not flakes
    assert not resilience._is_transient(NotImplementedError("x"))
    assert not resilience._is_transient(RecursionError("x"))


def test_journal_mode_rejects_split_checkpoint_resume_paths(tmp_path):
    with pytest.raises(SystemExit, match="SAME path"):
        _run_sweep(_sweep_cfg(checkpoint_path=str(tmp_path / "a.npz"),
                              resume_path=str(tmp_path / "b.npz")))


def test_injected_device_failure_retries_to_correct_stats():
    """Acceptance: an injected dispatch failure is retried with backoff
    and the run's stats are bit-identical to an undisturbed run."""
    from gossip_sim_tpu.cli import run_simulation

    def run(c):
        coll, _ = _fresh()
        run_simulation(c, "", coll, None, 0, "0", 0.0)
        return coll.collection[0].parity_snapshot()

    ref = run(_sweep_cfg(num_simulations=1))

    def hook(label, attempt):
        if label.startswith("measured-block") and attempt < 2:
            raise RuntimeError(f"injected failure at {label}")

    resilience.set_fault_hook(hook)
    try:
        c = _sweep_cfg(num_simulations=1, device_retries=2)
        c.device_backoff_s = 0.001
        snap = run(c)
    finally:
        resilience.set_fault_hook(None)
    for k in ref:
        assert ref[k] == snap[k], k
    assert get_registry().counter("resilience/device_failures") >= 2


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_injected_failure_cpu_fallback_flags_report():
    """Acceptance: --on-device-failure cpu-fallback completes the unit
    with correct stats and the run report flags it."""
    from gossip_sim_tpu.cli import run_simulation
    from gossip_sim_tpu.obs.report import build_run_report

    def run(c):
        coll, _ = _fresh()
        run_simulation(c, "", coll, None, 0, "0", 0.0)
        return coll.collection[0].parity_snapshot()

    ref = run(_sweep_cfg(num_simulations=1))

    def hook(label, attempt):
        if label.startswith("measured-block"):
            raise RuntimeError("dead device")

    resilience.set_fault_hook(hook)
    try:
        c = _sweep_cfg(num_simulations=1, device_retries=1,
                       on_device_failure="cpu-fallback")
        c.device_backoff_s = 0.001
        snap = run(c)
    finally:
        resilience.set_fault_hook(None)
    for k in ref:
        assert ref[k] == snap[k], k
    report = build_run_report(_sweep_cfg(), get_registry())
    assert report["resilience"]["fallback_units"] >= 1
    assert report["resilience"]["device_failures"] >= 2


def test_abort_exits_with_resumable_code_and_committed_journal(tmp_path):
    """Acceptance: --on-device-failure abort -> RESUMABLE_EXIT_CODE from
    the CLI, with every earlier unit committed in the journal."""
    from gossip_sim_tpu.cli import main

    ck = str(tmp_path / "abort.npz")
    fails = []

    def hook(label, attempt):
        # fail the second sweep sim's engine calls forever
        if label.startswith("warmup") and fails.count("armed") >= 1:
            raise RuntimeError("dead device")
        if label.startswith("warmup"):
            fails.append("armed")

    _fresh()
    resilience.set_fault_hook(hook)
    try:
        rc = main(["--num-synthetic-nodes", "48", "--iterations", "6",
                   "--warm-up-rounds", "2", "--test-type", "packet-loss",
                   "--num-simulations", "3", "--step-size", "0.1",
                   "--seed", "13", "--checkpoint-path", ck,
                   "--device-retries", "0", "--on-device-failure", "abort"])
    finally:
        resilience.set_fault_hook(None)
    assert rc == RESUMABLE_EXIT_CODE
    # sim 0 committed before sim 1's dispatch died
    with open(journal_path(ck)) as f:
        recs = [json.loads(ln) for ln in f.read().splitlines()]
    assert [r["unit"] for r in recs[1:]] == [0]


@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_cli_sigterm_returns_resumable_exit_code(tmp_path, monkeypatch):
    """kill-after-units (via the env hook — main() resets programmatic
    shutdown state on entry) sends a real SIGTERM through signal_guard;
    main() must finish the in-flight unit, commit, and return 75."""
    from gossip_sim_tpu.cli import main

    ck = str(tmp_path / "sig.npz")
    _fresh()
    monkeypatch.setenv(resilience.KILL_AFTER_ENV, "1")
    rc = main(["--num-synthetic-nodes", "48", "--iterations", "6",
               "--warm-up-rounds", "2", "--test-type", "packet-loss",
               "--num-simulations", "3", "--step-size", "0.1",
               "--seed", "13", "--checkpoint-path", ck])
    assert rc == RESUMABLE_EXIT_CODE
    with open(journal_path(ck)) as f:
        recs = [json.loads(ln) for ln in f.read().splitlines()]
    assert [r["unit"] for r in recs[1:]] == [0]
    # and the resumed CLI run completes cleanly
    monkeypatch.delenv(resilience.KILL_AFTER_ENV)
    _fresh()
    rc2 = main(["--num-synthetic-nodes", "48", "--iterations", "6",
                "--warm-up-rounds", "2", "--test-type", "packet-loss",
                "--num-simulations", "3", "--step-size", "0.1",
                "--seed", "13", "--checkpoint-path", ck, "--resume", ck])
    assert rc2 == 0


# --------------------------------------------------------------------------
# single-run autosave + satellites
# --------------------------------------------------------------------------

@pytest.mark.slow  # tier-1 budget; tools/resume_smoke gate covers this
def test_checkpoint_every_s_throttles_block_saves(tmp_path, monkeypatch):
    import gossip_sim_tpu.cli as cli
    from gossip_sim_tpu.checkpoint import load_state
    from gossip_sim_tpu.cli import run_simulation

    monkeypatch.setattr(cli, "HARVEST_BLOCK", 2)
    saves = []
    import gossip_sim_tpu.checkpoint as cp
    real = cp.save_state

    def counting_save(*a, **kw):
        saves.append(kw.get("iteration", a[4] if len(a) > 4 else None))
        return real(*a, **kw)

    monkeypatch.setattr(cp, "save_state", counting_save)
    ck = str(tmp_path / "single.npz")
    coll, _ = _fresh()
    # a huge interval: only the forced saves (post-warm-up + end) write
    run_simulation(_sweep_cfg(num_simulations=1, checkpoint_path=ck,
                              checkpoint_every_s=3600.0),
                   "", coll, None, 0, "0", 0.0)
    assert len(saves) == 2
    _, _, meta = load_state(ck)
    assert meta["iteration"] == 6

    saves.clear()
    coll, _ = _fresh()
    # interval 0 = the pre-resilience cadence: every measured block
    run_simulation(_sweep_cfg(num_simulations=1, checkpoint_path=ck),
                   "", coll, None, 0, "0", 0.0)
    # post-warm-up + two 2-round blocks + the forced end-of-run save
    assert len(saves) == 4


def test_heartbeat_carries_resumability_marker():
    from gossip_sim_tpu.obs import Heartbeat
    hb = Heartbeat(10, label="sweep", unit="sim")
    msg = hb.beat(3, force=True)
    assert "committed" not in msg
    hb.note_committed(3)
    msg = hb.beat(4, force=True)
    assert "committed 3/10, resumable" in msg


def test_run_report_resilience_keys_default_zero():
    from gossip_sim_tpu.obs import build_run_report, validate_run_report
    from gossip_sim_tpu.obs.spans import SpanRegistry
    report = build_run_report(Config(), SpanRegistry())
    assert validate_run_report(report) == []
    assert report["resilience"] == {
        "committed_units": 0, "resumed_units": 0,
        "device_failures": 0, "fallback_units": 0}


def test_run_report_write_is_atomic(tmp_path, monkeypatch):
    from gossip_sim_tpu.obs.report import write_run_report
    path = str(tmp_path / "report.json")
    write_run_report(path, {"ok": 1})
    good = open(path).read()

    real_replace = os.replace

    def boom(src, dst):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="disk full"):
        write_run_report(path, {"ok": 2})
    monkeypatch.setattr(os, "replace", real_replace)
    assert open(path).read() == good
    assert sorted(p.name for p in tmp_path.iterdir()) == ["report.json"]


def test_aggregate_state_dict_roundtrip():
    """AllOriginsStats sidecar snapshot: save after batch 1, load into a
    fresh instance, fold batch 2 — finalize must equal the straight-
    through accumulation."""
    from gossip_sim_tpu.cli import run_all_origins

    def cfg(**kw):
        return Config(num_synthetic_nodes=40, gossip_iterations=5,
                      warm_up_rounds=2, all_origins=True, origin_batch=20,
                      seed=9, **kw)

    _fresh()
    s = run_all_origins(cfg(), "", None, "0")
    agg = s["stats"]
    sd = agg.state_dict()
    from gossip_sim_tpu.identity import NodeIndex
    fresh_agg = type(agg)(agg.index, agg.hist_bins)
    fresh_agg.load_state_dict({k: np.asarray(v) for k, v in sd.items()})
    fresh_agg.finalize(cfg())
    assert fresh_agg.coverage_stats.mean == agg.coverage_stats.mean
    assert fresh_agg.rmr_stats.mean == agg.rmr_stats.mean
    assert (fresh_agg.hops_hist == agg.hops_hist).all()
    assert fresh_agg.measured_points == agg.measured_points
