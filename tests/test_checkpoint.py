"""Checkpoint/resume tests: a restored SimState must continue bit-identically
(a capability the reference lacks, SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.checkpoint import restore_sim_state, save_state
from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)


def _setup(n=48, o=2, seed=3):
    rng = np.random.default_rng(seed)
    stakes = rng.integers(1, 1 << 16, n).astype(np.int64) * 1_000_000_000
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=n, warm_up_rounds=0)
    origins = jnp.arange(o, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(seed), tables, origins, params)
    return params, tables, origins, state


def test_roundtrip_resume_is_bit_identical(tmp_path):
    params, tables, origins, state = _setup()
    state, _ = run_rounds(params, tables, origins, state, 3)
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params)

    # continue directly vs continue from the restored checkpoint
    cont_state, cont_rows = run_rounds(params, tables, origins, state, 4,
                                       start_it=3)
    restored, stored_params, _ = restore_sim_state(path, params)
    res_state, res_rows = run_rounds(params, tables, origins, restored, 4,
                                     start_it=3)

    assert stored_params["num_nodes"] == params.num_nodes
    for k in cont_rows:
        np.testing.assert_array_equal(np.asarray(cont_rows[k]),
                                      np.asarray(res_rows[k]), err_msg=k)
    for f in cont_state._fields:
        np.testing.assert_array_equal(np.asarray(getattr(cont_state, f)),
                                      np.asarray(getattr(res_state, f)),
                                      err_msg=f)


def test_shape_param_mismatch_rejected(tmp_path):
    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params)
    wrong = params._replace(num_nodes=params.num_nodes + 1)
    with pytest.raises(ValueError, match="num_nodes"):
        restore_sim_state(path, wrong)


def test_config_metadata_round_trips(tmp_path):
    from gossip_sim_tpu.config import Config

    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params, Config(gossip_push_fanout=9), iteration=7)
    _, _, meta = restore_sim_state(path, params)
    assert meta["config"]["gossip_push_fanout"] == 9
    assert meta["iteration"] == 7


def test_v1_checkpoint_backfills_derived_fields(tmp_path):
    """Round-4 checkpoints predate tfail/rc_shi/rc_slo; loading with the
    cluster tables must backfill them exactly."""
    import json

    params, tables, origins, state = _setup()
    state, _ = run_rounds(params, tables, origins, state, 5)
    path = str(tmp_path / "v1.npz")
    arrays = {f"state.{f}": np.asarray(getattr(state, f))
              for f in state._fields if f not in ("tfail", "rc_shi", "rc_slo")}
    meta = {"format_version": 1, "params": dict(params._asdict())}
    np.savez_compressed(path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)

    restored, _, _ = restore_sim_state(path, params, tables)
    for f in ("tfail", "rc_shi", "rc_slo"):
        np.testing.assert_array_equal(
            np.asarray(getattr(restored, f)),
            np.asarray(getattr(state, f)), err_msg=f)


def test_save_is_atomic_on_failure(tmp_path, monkeypatch):
    """A crash mid-save must never corrupt an existing checkpoint: the write
    goes to a temp file and only an os.replace publishes it."""
    import gossip_sim_tpu.checkpoint as cp

    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params)
    good = (tmp_path / "ckpt.npz").read_bytes()

    def _boom(*a, **kw):
        raise OSError("disk full")
    monkeypatch.setattr(cp.np, "savez_compressed", _boom)
    with pytest.raises(OSError, match="disk full"):
        save_state(path, state, params, iteration=9)
    # the prior checkpoint is untouched and no temp droppings remain
    assert (tmp_path / "ckpt.npz").read_bytes() == good
    assert [p.name for p in tmp_path.iterdir()] == ["ckpt.npz"]


def test_v3_checkpoint_records_impair_block(tmp_path):
    params, tables, origins, state = _setup()
    params = params._replace(packet_loss_rate=0.25, churn_fail_rate=0.01,
                             churn_recover_rate=0.5, partition_at=3,
                             heal_at=8, impair_seed=77)
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params, iteration=4)
    _, _, meta = restore_sim_state(path, params)
    assert meta["format_version"] == 9
    assert meta["impair"] == {
        "packet_loss_rate": 0.25, "churn_fail_rate": 0.01,
        "churn_recover_rate": 0.5, "partition_at": 3, "heal_at": 8,
        "impair_seed": 77}
    # v4: the pull meta block records the (default push) schedule
    assert meta["pull"]["gossip_mode"] == "push"


def test_v4_checkpoint_records_pull_block(tmp_path):
    params, tables, origins, state = _setup()
    params = params._replace(gossip_mode="push-pull", pull_fanout=4,
                             pull_interval=2, pull_bloom_fp_rate=0.2,
                             pull_request_cap=3)
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params, iteration=2)
    _, _, meta = restore_sim_state(path, params)
    assert meta["pull"] == {
        "gossip_mode": "push-pull", "pull_fanout": 4, "pull_interval": 2,
        "pull_bloom_fp_rate": 0.2, "pull_request_cap": 3}


def test_pre_v4_checkpoint_backfills_pull_state(tmp_path):
    """A checkpoint without the pull accumulators (pre-v4 writer) loads
    with exact zero backfill — no pull round ever ran before v4."""
    import numpy as np

    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params, iteration=1)
    # simulate a pre-v4 file: strip the pull arrays + meta block
    import json as _json
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"
                  and not k.endswith("pull_hops_hist_acc")
                  and not k.endswith("pull_rescued_acc")}
        meta = _json.loads(bytes(z["__meta__"]).decode())
    meta["format_version"] = 3
    meta.pop("pull", None)
    with open(path, "wb") as f:
        np.savez_compressed(f, __meta__=np.frombuffer(
            _json.dumps(meta).encode(), dtype=np.uint8), **arrays)
    restored, _, meta2 = restore_sim_state(path, params, tables)
    assert meta2["pull"]["gossip_mode"] == "push"
    assert (np.asarray(restored.pull_hops_hist_acc) == 0).all()
    assert (np.asarray(restored.pull_rescued_acc) == 0).all()


def test_v2_checkpoint_backfills_all_off_impair(tmp_path):
    """Pre-fault-subsystem checkpoints carry no impair block; loading must
    backfill the all-off defaults and stay resumable."""
    import json

    params, tables, origins, state = _setup()
    state, _ = run_rounds(params, tables, origins, state, 3)
    path = str(tmp_path / "v2.npz")
    arrays = {f"state.{f}": np.asarray(getattr(state, f))
              for f in state._fields}
    pdict = {k: v for k, v in params._asdict().items()
             if k not in ("packet_loss_rate", "churn_fail_rate",
                          "churn_recover_rate", "partition_at", "heal_at",
                          "impair_seed")}
    meta = {"format_version": 2, "params": pdict, "iteration": 3}
    np.savez_compressed(path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)

    restored, _, meta2 = restore_sim_state(path, params)
    assert meta2["impair"] == {
        "packet_loss_rate": 0.0, "churn_fail_rate": 0.0,
        "churn_recover_rate": 0.0, "partition_at": -1, "heal_at": -1,
        "impair_seed": 0}
    # and the restored state continues bit-identically
    cont, _ = run_rounds(params, tables, origins, state, 2, start_it=3)
    res, _ = run_rounds(params, tables, origins, restored, 2, start_it=3)
    for f in cont._fields:
        np.testing.assert_array_equal(np.asarray(getattr(cont, f)),
                                      np.asarray(getattr(res, f)), err_msg=f)


def test_roundtrip_resume_mid_churn_bit_identical(tmp_path):
    """Checkpoint taken mid-churn (nodes failed and recovering, partition
    open, loss active): because impairment decisions are stateless counter
    hashes of (seed, iteration, ids), a resume from the stored failed mask +
    iteration must be bit-exact with the uninterrupted run."""
    params, tables, origins, state = _setup()
    params = params._replace(packet_loss_rate=0.1, churn_fail_rate=0.05,
                             churn_recover_rate=0.3, partition_at=1,
                             heal_at=9, impair_seed=13)
    state, _ = run_rounds(params, tables, origins, state, 5)
    assert np.asarray(state.failed).any(), "churn regime must be mid-flight"
    path = str(tmp_path / "churn.npz")
    save_state(path, state, params, iteration=5)

    cont_state, cont_rows = run_rounds(params, tables, origins, state, 6,
                                       start_it=5)
    restored, _, meta = restore_sim_state(path, params)
    assert meta["iteration"] == 5
    res_state, res_rows = run_rounds(params, tables, origins, restored, 6,
                                     start_it=5)
    for k in cont_rows:
        np.testing.assert_array_equal(np.asarray(cont_rows[k]),
                                      np.asarray(res_rows[k]), err_msg=k)
    for f in cont_state._fields:
        np.testing.assert_array_equal(np.asarray(getattr(cont_state, f)),
                                      np.asarray(getattr(res_state, f)),
                                      err_msg=f)


def test_impair_knob_mismatch_warns_on_resume(tmp_path, caplog):
    import logging

    params, tables, origins, state = _setup()
    saved = params._replace(packet_loss_rate=0.2, impair_seed=3)
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, saved)
    with caplog.at_level(logging.WARNING):
        restore_sim_state(path, saved._replace(packet_loss_rate=0.4))
    assert any("impairment schedule" in r.message for r in caplog.records)


FIXTURE_DIR = __file__.rsplit("/", 1)[0] + "/fixtures/checkpoints"


@pytest.mark.parametrize("version", [1, 2, 3, 4, 5, 6, 7, 8])
def test_checkpoint_forward_compat_matrix(version):
    """Committed v1-v8 fixture files (tests/fixtures/checkpoints, frozen
    binaries from each format era) must load and restore forever — a new
    format can never silently orphan old checkpoints (ISSUE 7; v5 joined
    the matrix when checkpoint v6 landed, ISSUE 10; v6 when v7 landed,
    ISSUE 11; v7 when v8 landed, ISSUE 17).  Each fixture must
    (a) pass load_state's validation against current EngineParams,
    (b) restore to a full SimState with the era-appropriate backfills,
    (c) continue running on the current engine."""
    import json

    from gossip_sim_tpu.checkpoint import load_state

    path = f"{FIXTURE_DIR}/v{version}.npz"
    with np.load(path) as z:
        stakes = z["fixture.stakes"]
        meta_raw = json.loads(bytes(z["__meta__"]).decode())
    assert meta_raw["format_version"] == version
    tables = make_cluster_tables(stakes.astype(np.int64))
    params = EngineParams(num_nodes=16, warm_up_rounds=0)

    arrays, stored, meta = load_state(path, params)
    assert stored["num_nodes"] == 16
    # era backfills: pre-v3 impair all-off, pre-v4 pull mode "push",
    # pre-v5 resilience block empty
    if version < 3:
        assert meta["impair"]["packet_loss_rate"] == 0.0
        assert meta["impair"]["partition_at"] == -1
    if version < 4:
        assert meta["pull"]["gossip_mode"] == "push"
    if version < 5:
        assert meta["resilience"] == {}
    # pre-v6 backfills: traffic off, kind "sim"
    assert meta["traffic"]["traffic_values"] == 1
    assert meta["traffic"]["node_ingress_cap"] == 0
    assert meta["kind"] == "sim"
    # pre-v7 backfill: adaptive switch knobs at the engine defaults
    assert meta["adaptive"]["adaptive_switch_threshold"] == \
        EngineParams._field_defaults["adaptive_switch_threshold"]

    restored, _, _ = restore_sim_state(path, params, tables)
    for f in restored._fields:
        assert np.asarray(getattr(restored, f)).size >= 0, f
    if version == 1:
        # derived-field backfill must have produced real arrays
        assert np.asarray(restored.tfail).shape[-1] > 0
    if version < 4:
        assert (np.asarray(restored.pull_hops_hist_acc) == 0).all()
        assert (np.asarray(restored.pull_rescued_acc) == 0).all()
    if version < 7:
        # the adaptive direction bit did not exist — exact zero backfill
        assert not np.asarray(restored.adaptive_pull_on).any()
    # pre-v8 backfill: the health planes did not exist, and the gated-off
    # v8 writer carries them as identical zeros — either way, exact zeros
    assert not np.asarray(restored.health_prune_recv).any()
    assert not np.asarray(restored.health_first_round).any()
    assert meta["health"]["health"] is False
    # pre-v9 backfill: every earlier era wrote the dense representation
    assert meta["repr"]["representation"] == "dense"
    # the restored state must continue on the current engine
    origins = jnp.arange(1, dtype=jnp.int32)
    state, rows = run_rounds(params, tables, origins, restored, 2,
                             start_it=int(meta.get("iteration", 3)),
                             detail=True)
    assert np.asarray(rows["coverage"]).shape[0] == 2


def test_v5_checkpoint_records_resilience_block(tmp_path):
    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params, iteration=2,
               resilience={"journal": "ckpt.journal", "committed_units": 3})
    _, _, meta = restore_sim_state(path, params)
    assert meta["format_version"] == 9
    assert meta["resilience"] == {"journal": "ckpt.journal",
                                  "committed_units": 3}


def test_cli_kill_and_resume_bit_identical(tmp_path):
    """VERDICT r4 #6: a straight 16-iteration CLI run and a 10-iteration run
    killed + resumed to 16 must land on bit-identical final states."""
    from gossip_sim_tpu.cli import main
    from gossip_sim_tpu.identity import reset_unique_pubkeys

    base = ["--num-synthetic-nodes", "40", "--warm-up-rounds", "4",
            "--backend", "tpu", "--seed", "5"]
    full = str(tmp_path / "full.npz")
    part = str(tmp_path / "part.npz")
    # the synthetic cluster derives pubkeys from the new_unique counter;
    # reset it so all three runs build the identical cluster
    reset_unique_pubkeys()
    assert main(base + ["--iterations", "16",
                        "--checkpoint-path", full]) == 0
    reset_unique_pubkeys()
    assert main(base + ["--iterations", "10",
                        "--checkpoint-path", part]) == 0
    reset_unique_pubkeys()
    assert main(base + ["--iterations", "16", "--resume", part,
                        "--checkpoint-path", part]) == 0

    with np.load(full) as zf, np.load(part) as zp:
        assert set(zf.files) == set(zp.files)
        for k in zf.files:
            if k == "__meta__":
                continue
            np.testing.assert_array_equal(zf[k], zp[k], err_msg=k)


def test_v6_traffic_checkpoint_roundtrip_and_kind_guard(tmp_path):
    """kind="traffic" v6 checkpoints: TrafficState + serialized
    TrafficStats round-trip exactly, and the two restore entry points
    refuse each other's kinds with a clear error (ISSUE 10)."""
    from gossip_sim_tpu.checkpoint import (restore_traffic_state,
                                           save_traffic_state)
    from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                               init_traffic_state,
                                               run_traffic_rounds)

    rng = np.random.default_rng(5)
    stakes = rng.integers(1, 1 << 16, 16).astype(np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    tparams = EngineParams(num_nodes=16, traffic_values=3, traffic_rate=1,
                           node_ingress_cap=4, warm_up_rounds=0).validate()
    tt = device_traffic_tables(stakes)
    tstate = init_traffic_state(stakes, tparams, seed=3)
    tstate, _ = run_traffic_rounds(tparams, tables, tt, tstate, 3)
    path = str(tmp_path / "traffic.npz")
    stats_state = {"iterations": [0, 1, 2], "rounds": {}, "records": [],
                   "final": {}}
    save_traffic_state(path, tstate, tparams, iteration=3,
                       traffic_stats=stats_state)
    restored, stored, meta = restore_traffic_state(path, tparams)
    assert meta["kind"] == "traffic"
    assert meta["format_version"] == 9
    assert meta["traffic"]["traffic_values"] == 3
    assert meta["traffic_stats"]["iterations"] == [0, 1, 2]
    for f, a, b in zip(restored._fields, restored, tstate):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f)
    # continuation runs on the restored state
    st2, rows = run_traffic_rounds(tparams, tables, tt, restored, 2,
                                   start_it=3)
    assert np.asarray(rows["injected"]).shape[0] == 2
    # kind guards, both directions
    with pytest.raises(ValueError, match="traffic"):
        restore_sim_state(path, EngineParams(num_nodes=16))
    params, tables16, origins, state = _setup()
    sim_path = str(tmp_path / "sim.npz")
    save_state(sim_path, state, params, iteration=1)
    with pytest.raises(ValueError, match="sim"):
        restore_traffic_state(sim_path)


def test_v8_checkpoint_roundtrips_nonzero_health_planes(tmp_path):
    """A health-gated sim run accumulates nonzero health planes; a v8
    checkpoint must carry them through save/restore bit-exactly and
    record the gate in the health meta block (ISSUE 17)."""
    params, tables, origins, state = _setup()
    params = params._replace(health=True)
    state = state._replace(
        health_prune_recv=state.health_prune_recv + 3,
        health_first_round=state.health_first_round + 7)
    path = str(tmp_path / "v8.npz")
    save_state(path, state, params, iteration=4)
    restored, _, meta = restore_sim_state(path, params)
    assert meta["format_version"] == 9
    assert meta["health"] == {"health": True}
    np.testing.assert_array_equal(np.asarray(restored.health_prune_recv),
                                  np.asarray(state.health_prune_recv))
    np.testing.assert_array_equal(np.asarray(restored.health_first_round),
                                  np.asarray(state.health_first_round))


def test_pre_v8_traffic_checkpoint_backfills_health_planes(tmp_path):
    """A v7-era traffic checkpoint (no health planes) must restore with
    exact zero backfill — the gated-off engine never incremented them."""
    import json as _json

    from gossip_sim_tpu.checkpoint import (restore_traffic_state,
                                           save_traffic_state)
    from gossip_sim_tpu.engine.traffic import init_traffic_state

    rng = np.random.default_rng(9)
    stakes = rng.integers(1, 1 << 16, 16).astype(np.int64) * 10**9
    tparams = EngineParams(num_nodes=16, traffic_values=3,
                           warm_up_rounds=0).validate()
    tstate = init_traffic_state(stakes, tparams, seed=3)
    path = str(tmp_path / "traffic_v7.npz")
    save_traffic_state(path, tstate, tparams, iteration=1)
    # rewrite as a v7-era file: strip the health arrays + meta block
    health = ("health_prune_recv", "health_lat_acc", "health_del_acc",
              "health_rescued_acc")
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"
                  and k[len("state."):] not in health}
        meta = _json.loads(bytes(z["__meta__"]).decode())
    meta["format_version"] = 7
    meta.pop("health", None)
    meta["params"].pop("health", None)
    with open(path, "wb") as f:
        np.savez_compressed(f, __meta__=np.frombuffer(
            _json.dumps(meta).encode(), dtype=np.uint8), **arrays)
    restored, _, meta2 = restore_traffic_state(path, tparams)
    assert meta2["health"] == {"health": False}
    for fld in health:
        plane = np.asarray(getattr(restored, fld))
        assert plane.shape == (16,) and not plane.any(), fld


def test_health_gate_mismatch_warns_on_resume(tmp_path, caplog):
    """Resuming a gate-off checkpoint with --health on (or vice versa)
    must warn: the planes only cover rounds run under an enabled gate."""
    import logging

    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params)     # health=False default
    with caplog.at_level(logging.WARNING):
        restore_sim_state(path, params._replace(health=True))
    assert any("health planes" in r.message for r in caplog.records)


@pytest.mark.parametrize("write_repr,read_repr",
                         [("dense", "sparse"), ("sparse", "dense")])
def test_v9_cross_representation_resume_bit_identical(
        tmp_path, write_repr, read_repr):
    """v9 stamps the representation and restore_sim_state reshapes the rc
    stake planes to the CURRENT params (collapse to [O,N,0] for sparse;
    re-derive via the cluster tables for dense): a checkpoint written
    under either representation must continue bit-identically to a
    never-checkpointed run under the other."""
    n, o = 48, 2
    rng = np.random.default_rng(3)
    stakes = rng.integers(1, 1 << 16, n).astype(np.int64) * 10**9
    tables = make_cluster_tables(stakes)
    origins = jnp.arange(o, dtype=jnp.int32)

    def params_for(r):
        return EngineParams(num_nodes=n, warm_up_rounds=0,
                            representation=r).validate()

    wp = params_for(write_repr)
    state = init_state(jax.random.PRNGKey(3), tables, origins, wp)
    state, _ = run_rounds(wp, tables, origins, state, 3)
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, wp, iteration=3)

    rp = params_for(read_repr)
    restored, _, meta = restore_sim_state(path, rp, tables)
    assert meta["format_version"] == 9
    assert meta["repr"]["representation"] == write_repr
    width = 0 if read_repr == "sparse" \
        else np.asarray(state.rc_src).shape[-1]
    assert np.asarray(restored.rc_shi).shape[-1] == width
    _, rows = run_rounds(rp, tables, origins, restored, 3, start_it=3,
                         detail=True)

    ref = init_state(jax.random.PRNGKey(3), tables, origins, rp)
    ref, _ = run_rounds(rp, tables, origins, ref, 3)
    _, ref_rows = run_rounds(rp, tables, origins, ref, 3, start_it=3,
                             detail=True)
    for k in ref_rows:
        np.testing.assert_array_equal(
            np.asarray(rows[k]), np.asarray(ref_rows[k]), err_msg=k)
