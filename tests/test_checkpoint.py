"""Checkpoint/resume tests: a restored SimState must continue bit-identically
(a capability the reference lacks, SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_sim_tpu.checkpoint import restore_sim_state, save_state
from gossip_sim_tpu.engine import (EngineParams, init_state,
                                   make_cluster_tables, run_rounds)


def _setup(n=48, o=2, seed=3):
    rng = np.random.default_rng(seed)
    stakes = rng.integers(1, 1 << 16, n).astype(np.int64) * 1_000_000_000
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=n, warm_up_rounds=0)
    origins = jnp.arange(o, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(seed), tables, origins, params)
    return params, tables, origins, state


def test_roundtrip_resume_is_bit_identical(tmp_path):
    params, tables, origins, state = _setup()
    state, _ = run_rounds(params, tables, origins, state, 3)
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params)

    # continue directly vs continue from the restored checkpoint
    cont_state, cont_rows = run_rounds(params, tables, origins, state, 4,
                                       start_it=3)
    restored, stored_params, _ = restore_sim_state(path, params)
    res_state, res_rows = run_rounds(params, tables, origins, restored, 4,
                                     start_it=3)

    assert stored_params["num_nodes"] == params.num_nodes
    for k in cont_rows:
        np.testing.assert_array_equal(np.asarray(cont_rows[k]),
                                      np.asarray(res_rows[k]), err_msg=k)
    for f in cont_state._fields:
        np.testing.assert_array_equal(np.asarray(getattr(cont_state, f)),
                                      np.asarray(getattr(res_state, f)),
                                      err_msg=f)


def test_shape_param_mismatch_rejected(tmp_path):
    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params)
    wrong = params._replace(num_nodes=params.num_nodes + 1)
    with pytest.raises(ValueError, match="num_nodes"):
        restore_sim_state(path, wrong)


def test_config_metadata_round_trips(tmp_path):
    from gossip_sim_tpu.config import Config

    params, tables, origins, state = _setup()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, state, params, Config(gossip_push_fanout=9))
    _, _, meta = restore_sim_state(path, params)
    assert meta["config"]["gossip_push_fanout"] == 9
