"""Exact ports of the reference's end-to-end engine tests: ``test_mst``
(gossip.rs:1040-1163), ``test_nth_largest`` (gossip_main.rs:1056-1069) and
``test_pruning`` (gossip_main.rs:1071-1163)."""

import pytest

from gossip_sim_tpu.constants import LAMPORTS_PER_SOL, UNREACHED
from gossip_sim_tpu.identity import pubkey_new_unique
from gossip_sim_tpu.oracle.cluster import Cluster, Node
from gossip_sim_tpu.oracle.rustrng import ChaChaRng

MAX_STAKE = (1 << 20) * LAMPORTS_PER_SOL


def make_seeded_cluster(n_extra=5, seed=189):
    """Reference fixture recipe (gossip.rs:1044-1064): n counter-pubkeys plus
    one more as origin, ChaCha-seeded stakes, nodes sorted by pubkey bytes."""
    node_keys = [pubkey_new_unique() for _ in range(n_extra)]
    rng = ChaChaRng.from_seed_byte(seed)
    pubkey = pubkey_new_unique()
    stakes = {pk: rng.gen_range_u64(1, MAX_STAKE) for pk in node_keys}
    stakes[pubkey] = rng.gen_range_u64(1, MAX_STAKE)
    nodes = sorted((Node(pk, s) for pk, s in stakes.items()),
                   key=lambda nd: nd.pubkey.raw)
    return nodes, stakes, pubkey, rng


def init_gossip(rng, nodes, stakes, active_set_size):
    for node in nodes:
        node.initialize_gossip(rng, stakes, active_set_size)


def find_nth_largest_node(n, nodes):
    """Min-heap nth-largest-stake origin selection
    (gossip_main.rs:279-290)."""
    import heapq
    heap = []
    for node in nodes:
        stake = node.stake if hasattr(node, "stake") else node[1]
        if len(heap) < n:
            heapq.heappush(heap, stake)
        elif stake >= heap[0]:
            heapq.heapreplace(heap, stake)
    if not heap:
        return None
    target = heap[0]
    for node in nodes:
        stake = node.stake if hasattr(node, "stake") else node[1]
        if stake == target:
            return node
    return None


def test_nth_largest():
    stakes = [10, 123, 67, 18, 29, 567, 12, 5, 875, 234, 12, 5, 76, 0, 12354, 985]
    ranks = [5, 10, 12, 1, 6, 2, 9, 16]
    expected = [234, 18, 12, 12354, 123, 985, 29, 0]
    nodes = [(pubkey_new_unique(), s) for s in stakes]
    for rank, want in zip(ranks, expected):
        got = find_nth_largest_node(rank, nodes)
        assert got[1] == want


def test_mst():
    PUSH_FANOUT, ACTIVE_SET_SIZE = 2, 12
    nodes, stakes, origin, rng = make_seeded_cluster()
    init_gossip(rng, nodes, stakes, ACTIVE_SET_SIZE)
    node_map = {nd.pubkey: nd for nd in nodes}
    cluster = Cluster(PUSH_FANOUT)
    cluster.run_gossip(origin, stakes, node_map)

    pk = [nd.pubkey for nd in nodes]
    assert len(cluster.visited) == 6
    # distances (gossip.rs:1093-1098)
    assert [cluster.distances[pk[i]] for i in range(6)] == [2, 3, 1, 2, 1, 0]
    # inbound counts (gossip.rs:1101-1105)
    assert [len(cluster.orders[pk[i]]) for i in range(5)] == [3, 1, 3, 2, 3]
    # per-edge hops (gossip.rs:1109-1127)
    assert cluster.orders[pk[0]][pk[1]] == 4
    assert cluster.orders[pk[0]][pk[4]] == 2
    assert cluster.orders[pk[1]][pk[0]] == 3
    assert cluster.orders[pk[2]][pk[0]] == 3
    assert cluster.orders[pk[2]][pk[3]] == 3
    assert cluster.orders[pk[2]][pk[5]] == 1
    assert cluster.orders[pk[4]][pk[2]] == 2
    assert cluster.orders[pk[4]][pk[3]] == 3
    assert cluster.orders[pk[4]][pk[5]] == 1
    # origin absent from orders (gossip.rs:1131)
    assert pk[5] not in cluster.orders
    # full coverage (gossip.rs:1134)
    assert cluster.coverage(stakes) == (1.0, 0)
    # MST edges (gossip.rs:1138-1155)
    assert len(cluster.mst[pk[5]]) == 2
    assert pk[4] in cluster.mst[pk[5]] and pk[2] in cluster.mst[pk[5]]
    assert len(cluster.mst[pk[4]]) == 2
    assert pk[0] in cluster.mst[pk[4]] and pk[3] in cluster.mst[pk[4]]
    assert len(cluster.mst[pk[0]]) == 1
    assert pk[1] in cluster.mst[pk[0]]
    assert pk[1] not in cluster.mst
    assert pk[3] not in cluster.mst
    assert pk[4] not in cluster.mst[pk[0]]
    assert pk[5] not in cluster.mst[pk[4]]


def test_pruning():
    # gossip_main.rs:1071-1163: no prunes until iteration 19 (upsert gate),
    # then exact pruner -> prunee pairs.
    PUSH_FANOUT, ACTIVE_SET_SIZE = 2, 12
    PRUNE_STAKE_THRESHOLD, MIN_INGRESS_NODES = 0.15, 2
    CHANCE_TO_ROTATE, GOSSIP_ITERATIONS = 0.2, 21
    nodes, stakes, origin, rng = make_seeded_cluster()
    init_gossip(rng, nodes, stakes, ACTIVE_SET_SIZE)
    cluster = Cluster(PUSH_FANOUT)
    pk = [nd.pubkey for nd in nodes]
    # The reference drives rotation from a separate entropy rng
    # (gossip.rs:747-753); we use a separate seeded one.  With <= 12
    # candidates rotation never changes membership, so goldens hold.
    rot_rng = ChaChaRng.from_seed_byte(7)
    node_map = {nd.pubkey: nd for nd in nodes}
    for i in range(GOSSIP_ITERATIONS):
        cluster.run_gossip(origin, stakes, node_map)
        assert len(cluster.visited) == 6
        cluster.consume_messages(origin, nodes)
        cluster.send_prunes(origin, nodes, PRUNE_STAKE_THRESHOLD,
                            MIN_INGRESS_NODES, stakes)
        prunes = cluster.prunes
        assert len(prunes) == 6
        for pruner, prune in prunes.items():
            if i <= 18:
                assert len(prune) == 0
            for prunee in prune:
                if pruner == pk[2]:
                    assert prunee == pk[0]
                elif pruner == pk[0]:
                    assert prunee == pk[1]
                elif pruner == pk[4]:
                    assert prunee == pk[3]
        if i == 19:
            # the three expected prunes fired
            assert sum(len(p) for p in prunes.values()) == 3
        cluster.prune_connections(node_map, stakes)
        cluster.chance_to_rotate(rot_rng, nodes, ACTIVE_SET_SIZE, stakes,
                                 CHANCE_TO_ROTATE)


def test_fail_nodes():
    nodes, stakes, origin, rng = make_seeded_cluster(n_extra=19)
    init_gossip(rng, nodes, stakes, 12)
    cluster = Cluster(3)
    cluster.fail_nodes(0.25, nodes, ChaChaRng.from_seed_byte(5))
    assert sum(nd.failed for nd in nodes) == 5
    node_map = {nd.pubkey: nd for nd in nodes}
    if node_map[origin].failed:
        pytest.skip("origin failed in this draw")
    cluster.run_gossip(origin, stakes, node_map)
    # failed nodes are never reached and never counted stranded
    for nd in nodes:
        if nd.failed:
            assert cluster.distances[nd.pubkey] == UNREACHED
            assert nd.pubkey not in cluster.stranded_nodes()


def test_debug_dumps(caplog):
    """The reference's debug-level dumps (gossip.rs:365-431): hops, node
    orders, MST, pushes, prunes all emit under DEBUG."""
    import logging

    nodes, stakes, origin, rng = make_seeded_cluster()
    init_gossip(rng, nodes, stakes, 12)
    cluster = Cluster(2)
    node_map = {nd.pubkey: nd for nd in nodes}
    cluster.run_gossip(origin, stakes, node_map)
    cluster.consume_messages(origin, nodes)
    cluster.send_prunes(origin, nodes, 0.15, 2, stakes)
    with caplog.at_level(logging.DEBUG,
                         logger="gossip_sim_tpu.oracle.cluster"):
        cluster.print_hops()
        cluster.print_node_orders()
        cluster.print_mst()
        cluster.print_pushes()
        cluster.print_prunes()
    text = caplog.text
    for banner in ("DISTANCES FROM ORIGIN", "NODE ORDERS", "MST:",
                   "PUSHES:", "PRUNES:"):
        assert banner in text
    # every non-origin reached node appears in the orders dump
    n_dests = sum(1 for pk in cluster.orders if pk != origin)
    assert text.count("----- dest node, num_inbound:") == n_dests
