"""Gossip-as-a-service subsystem tests (serve/, ISSUE 20).

Three layers, matching the daemon's decomposition:

* **engine/lanes.py dynamic membership** — the execution primitive: a
  single-lane blocked run is bit-identical to the one-shot lane path,
  K co-resident lanes with different seeds/origins/knobs (including a
  gate-union lane riding the impaired graph at its off endpoint) are
  each bit-identical to their solo runs, admission via
  ``splice_lane_state`` is a bit-exact no-op for surviving lanes, and
  steady-state admissions never recompile (only a gate-union widening
  does, exactly once).  These four proofs are compile-heavy (~40 s of
  CPU jit) and marked ``slow``; tools/serve_smoke.py gates the same
  contracts end-to-end every CI run.
* **serve/admission.py** — the ledger-driven controller: 413 over
  budget, fits-the-machine-not-the-moment queuing, 429 backpressure,
  FIFO-per-tenant round-robin fairness, byte-reservation release.
* **serve/request.py + events v2** — request validation (unknown knobs
  are errors, rates range-checked, ids sanitized) and the serve event
  lifecycle: serve events carry the v2 schema tag while non-serve runs
  still emit pure v1 logs that v1 consumers keep validating.
"""
import json
import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

from gossip_sim_tpu.config import Config
from gossip_sim_tpu.engine import (EngineParams, broadcast_state,
                                   clear_dyn_lane_cache,
                                   dyn_lane_cache_size, init_state,
                                   lane_state, make_cluster_tables,
                                   merge_lane_statics, run_rounds,
                                   run_rounds_lanes, run_rounds_lanes_dyn,
                                   splice_lane_state, stack_knobs,
                                   stack_origins)
from gossip_sim_tpu.obs.telemetry import (EVENT_SCHEMA, EVENT_SCHEMA_V2,
                                          get_hub, validate_event)
from gossip_sim_tpu.serve import (AdmissionController, RejectedRequest,
                                  ScenarioRequest, block_rounds,
                                  parse_request)

N = 96
TOTAL = 12
BLOCK = 4


def _cluster(n=N, seed=11):
    rng = np.random.default_rng(seed)
    stakes = rng.choice(np.arange(1, 50 * n), size=n,
                        replace=False).astype(np.int64) * 10**9
    return make_cluster_tables(stakes)


def _solo(params, tables, org, key, rounds=TOTAL):
    state = init_state(jax.random.PRNGKey(key), tables, org, params)
    state, rows = run_rounds(params, tables, org, state, rounds)
    return (jax.tree_util.tree_map(np.asarray, state),
            jax.tree_util.tree_map(np.asarray, rows))


def _np(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _assert_tree_equal(a, b, what):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)


# --------------------------------------------------------------------------
# scheduler arithmetic
# --------------------------------------------------------------------------

def test_block_rounds_largest_divisor():
    assert block_rounds(60, 5) == 5
    assert block_rounds(60, 7) == 6      # largest divisor <= 7
    assert block_rounds(7, 5) == 1       # prime total: fall back to 1
    assert block_rounds(12, 100) == 12   # requested past total: one block
    assert block_rounds(12, 0) == 1
    for total, req in [(60, 5), (60, 7), (48, 9), (100, 13)]:
        b = block_rounds(total, req)
        assert total % b == 0 and b <= max(1, min(req, total))


def test_stack_origins_validates_widths():
    o = stack_origins([jnp.asarray([1], jnp.int32),
                       jnp.asarray([4], jnp.int32)])
    assert o.shape == (2, 1) and o.dtype == jnp.int32
    with pytest.raises(ValueError):
        stack_origins([jnp.asarray([1], jnp.int32),
                       jnp.asarray([2, 3], jnp.int32)])
    with pytest.raises(ValueError):
        stack_origins([])


# --------------------------------------------------------------------------
# dynamic lane membership: the daemon's execution primitive
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_dyn_blocked_single_lane_bit_equals_one_shot():
    # TOTAL rounds in BLOCK-round pieces through run_rounds_lanes_dyn
    # must equal the one-shot static lane path bit for bit: the traced
    # per-lane start_its reproduces arange(num_iters) + start_it exactly
    params = EngineParams(num_nodes=N)
    tables = _cluster()
    org = jnp.asarray([2], jnp.int32)
    static = params.static_part()
    knobs = stack_knobs([params.knob_values()])

    base = init_state(jax.random.PRNGKey(7), tables, org, params)
    ref_states, ref_rows = run_rounds_lanes(
        static, tables, org, broadcast_state(base, 1), knobs, TOTAL)
    ref_states, ref_rows = _np(ref_states), _np(ref_rows)

    states = broadcast_state(
        init_state(jax.random.PRNGKey(7), tables, org, params), 1)
    ostack = stack_origins([org])
    chunks = []
    for off in range(0, TOTAL, BLOCK):
        states, rows = run_rounds_lanes_dyn(
            static, tables, ostack, states, knobs, BLOCK,
            jnp.asarray([off], jnp.int32))
        chunks.append(_np(rows))
    # rows are time-major [num_iters, K, ...]: stitch the blocks in time
    got_rows = {k: np.concatenate([c[k] for c in chunks], axis=0)
                for k in chunks[0]}
    assert set(got_rows) == set(ref_rows)
    for k in ref_rows:
        np.testing.assert_array_equal(got_rows[k], np.asarray(ref_rows[k]),
                                      err_msg=f"rows[{k}]")
    _assert_tree_equal(_np(states), ref_states, "final state")


@pytest.mark.slow
def test_dyn_mixed_lanes_bit_equal_solo_runs():
    # two co-resident scenarios: different seeds, origins, traced knob
    # values AND impairment gates — the union static runs the loss-free
    # lane through the loss-gated graph at rate 0 bit-identically
    tables = _cluster()
    p0 = EngineParams(num_nodes=N, packet_loss_rate=0.1,
                      probability_of_rotation=0.2)
    p1 = EngineParams(num_nodes=N)           # no loss gate of its own
    org0 = jnp.asarray([1], jnp.int32)
    org1 = jnp.asarray([5], jnp.int32)
    union = merge_lane_statics([p0.static_part(), p1.static_part()])
    assert union.has_loss and not p1.static_part().has_loss
    knobs = stack_knobs([p0.knob_values(), p1.knob_values()])
    ostack = stack_origins([org0, org1])

    s0 = init_state(jax.random.PRNGKey(3), tables, org0, p0)
    s1 = init_state(jax.random.PRNGKey(9), tables, org1, p1)
    states = splice_lane_state(broadcast_state(s0, 2), 1, s1)
    chunks = []
    for off in range(0, TOTAL, BLOCK):
        states, rows = run_rounds_lanes_dyn(
            union, tables, ostack, states, knobs, BLOCK,
            jnp.asarray([off, off], jnp.int32))
        chunks.append(_np(rows))
    got = {k: np.concatenate([c[k] for c in chunks], axis=0)
           for k in chunks[0]}

    ref0_state, ref0_rows = _solo(p0, tables, org0, key=3)
    ref1_state, ref1_rows = _solo(p1, tables, org1, key=9)
    for lane, ref_rows in ((0, ref0_rows), (1, ref1_rows)):
        for k in ref_rows:
            np.testing.assert_array_equal(
                got[k][:, lane], np.asarray(ref_rows[k]),
                err_msg=f"lane {lane} rows[{k}]")
    _assert_tree_equal(lane_state(_np(states), 0), ref0_state,
                       "lane 0 state")
    _assert_tree_equal(lane_state(_np(states), 1), ref1_state,
                       "lane 1 state")


@pytest.mark.slow
def test_dyn_admission_splice_is_noop_for_survivor():
    # lane 0 retires mid-stream and a NEW request is spliced in (fresh
    # state, new origin, new start offset 0) while lane 1 keeps running:
    # lane 1's remaining rows and final state must not move by one bit
    tables = _cluster()
    params = EngineParams(num_nodes=N)
    static = params.static_part()
    org_a = jnp.asarray([1], jnp.int32)   # short request in lane 0
    org_b = jnp.asarray([4], jnp.int32)   # survivor in lane 1
    org_c = jnp.asarray([7], jnp.int32)   # admitted into lane 0 later
    knobs = stack_knobs([params.knob_values(), params.knob_values()])

    sa = init_state(jax.random.PRNGKey(1), tables, org_a, params)
    sb = init_state(jax.random.PRNGKey(2), tables, org_b, params)
    states = splice_lane_state(broadcast_state(sa, 2), 1, sb)
    ostack = stack_origins([org_a, org_b])
    survivor_rows = []
    # block 1: both run their first BLOCK rounds
    states, rows = run_rounds_lanes_dyn(
        static, tables, ostack, states, knobs, BLOCK,
        jnp.asarray([0, 0], jnp.int32))
    survivor_rows.append(_np(rows))
    # lane 0 "retires": admit request c at offset 0, survivor continues
    sc = init_state(jax.random.PRNGKey(5), tables, org_c, params)
    states = splice_lane_state(states, 0, sc)
    ostack = stack_origins([org_c, org_b])
    for off in range(BLOCK, TOTAL, BLOCK):
        states, rows = run_rounds_lanes_dyn(
            static, tables, ostack, states, knobs, BLOCK,
            jnp.asarray([off - BLOCK, off], jnp.int32))
        survivor_rows.append(_np(rows))
    got_b = {k: np.concatenate([c[k][:, 1] for c in survivor_rows], axis=0)
             for k in survivor_rows[0]}

    ref_state, ref_rows = _solo(params, tables, org_b, key=2)
    for k in ref_rows:
        np.testing.assert_array_equal(got_b[k], np.asarray(ref_rows[k]),
                                      err_msg=f"survivor rows[{k}]")
    _assert_tree_equal(lane_state(_np(states), 1), ref_state,
                       "survivor state")


@pytest.mark.slow
def test_dyn_steady_state_zero_recompiles_gate_union_once():
    # the serve compile contract: admissions with new knob VALUES, new
    # origins, and new start offsets re-enter the one warm executable;
    # only widening the impairment gate union compiles — exactly once
    tables = _cluster()
    params = EngineParams(num_nodes=N)
    static = params.static_part()
    org = jnp.asarray([1], jnp.int32)
    ostack = stack_origins([org, org])
    base = init_state(jax.random.PRNGKey(0), tables, org, params)
    states = broadcast_state(base, 2)
    knobs = stack_knobs([params.knob_values(), params.knob_values()])

    clear_dyn_lane_cache()
    states, _ = run_rounds_lanes_dyn(static, tables, ostack, states,
                                     knobs, BLOCK,
                                     jnp.asarray([0, 0], jnp.int32))
    assert dyn_lane_cache_size() == 1
    # steady state: different knob values / origins / offsets — no compile
    p2 = params._replace(probability_of_rotation=0.31)
    knobs2 = stack_knobs([p2.knob_values(), params.knob_values()])
    ostack2 = stack_origins([jnp.asarray([8], jnp.int32), org])
    states, _ = run_rounds_lanes_dyn(static, tables, ostack2, states,
                                     knobs2, BLOCK,
                                     jnp.asarray([4, 8], jnp.int32))
    assert dyn_lane_cache_size() == 1
    # gate-union widening (first lossy admission): one new executable
    lossy = params._replace(packet_loss_rate=0.05)
    union = merge_lane_statics([lossy.static_part(), static])
    knobs3 = stack_knobs([lossy.knob_values(), params.knob_values()])
    states, _ = run_rounds_lanes_dyn(union, tables, ostack, states,
                                     knobs3, BLOCK,
                                     jnp.asarray([0, 0], jnp.int32))
    assert dyn_lane_cache_size() == 2
    # further lossy admissions ride the widened executable
    lossy2 = params._replace(packet_loss_rate=0.08)
    knobs4 = stack_knobs([lossy2.knob_values(), lossy.knob_values()])
    states, _ = run_rounds_lanes_dyn(union, tables, ostack, states,
                                     knobs4, BLOCK,
                                     jnp.asarray([4, 4], jnp.int32))
    assert dyn_lane_cache_size() == 2


# --------------------------------------------------------------------------
# admission control (serve/admission.py)
# --------------------------------------------------------------------------

def _req(rid, tenant="t", bytes_=100):
    r = ScenarioRequest(id=rid, tenant=tenant)
    r.predicted_bytes = bytes_
    return r


def test_admission_413_over_budget_carries_ledger_detail():
    adm = AdmissionController(budget_bytes=1000)
    with pytest.raises(RejectedRequest) as ei:
        adm.submit(_req("big", bytes_=2000))
    e = ei.value
    assert e.code == 413
    assert e.payload()["predicted_bytes"] == 2000
    assert e.payload()["budget_bytes"] == 1000
    assert adm.counters == {"received": 1, "admitted": 0, "rejected": 1,
                            "completed": 0}
    assert adm.tenants_rejected == {"t": 1}


def test_admission_fits_machine_not_moment_waits_for_completion():
    adm = AdmissionController(budget_bytes=1000)
    r1, r2 = _req("r1", bytes_=600), _req("r2", bytes_=600)
    adm.submit(r1)
    adm.submit(r2)                        # fits the machine: queued, not 413
    assert adm.next_admission() is r1
    assert adm.bytes_in_use() == 600
    assert adm.next_admission() is None   # not the moment
    adm.complete(r1)
    assert adm.bytes_in_use() == 0
    assert adm.next_admission() is r2


def test_admission_429_queue_full():
    adm = AdmissionController(max_queue=1)
    adm.submit(_req("q1"))
    with pytest.raises(RejectedRequest) as ei:
        adm.submit(_req("q2"))
    assert ei.value.code == 429


def test_admission_round_robin_is_fair_across_tenants():
    # alice sprays 3 requests before bob's 1 arrives; bob still runs 2nd
    adm = AdmissionController()
    a1, a2, a3 = (_req(f"a{i}", tenant="alice") for i in (1, 2, 3))
    b1 = _req("b1", tenant="bob")
    for r in (a1, a2, a3, b1):
        adm.submit(r)
    order = [adm.next_admission().id for _ in range(4)]
    assert order == ["a1", "b1", "a2", "a3"]
    assert adm.tenants_admitted == {"alice": 3, "bob": 1}


def test_admission_unmetered_budget_reports_unlimited():
    adm = AdmissionController(budget_bytes=0)
    assert adm.available_bytes() == -1
    adm.submit(_req("r", bytes_=10**15))  # no budget: any size queues


# --------------------------------------------------------------------------
# request schema (serve/request.py)
# --------------------------------------------------------------------------

def _base_config():
    return Config(num_synthetic_nodes=150, gossip_iterations=20,
                  warm_up_rounds=4, seed=3, serve=True)


def test_parse_request_rejects_unknown_knob_and_bad_rates():
    base = _base_config()
    with pytest.raises(ValueError, match="unknown knob"):
        parse_request({"id": "r", "knobs": {"bogus": 1}}, base,
                      default_id="d")
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        parse_request({"id": "r", "knobs": {"packet_loss_rate": 1.5}},
                      base, default_id="d")
    with pytest.raises(ValueError, match="not JSON"):
        parse_request(b"{nope", base, default_id="d")
    with pytest.raises(ValueError, match="JSON object"):
        parse_request([1, 2], base, default_id="d")
    with pytest.raises(ValueError, match="origin_rank"):
        parse_request({"origin_rank": 0}, base, default_id="d")
    with pytest.raises(ValueError, match="bad request id"):
        parse_request({"id": "has space"}, base, default_id="d")


def test_parse_request_defaults_and_spec_roundtrip():
    base = _base_config()
    req = parse_request(json.dumps({"tenant": "alice", "seed": 259,
                                    "knobs": {"packet_loss_rate": 0.05}}),
                        base, default_id="gen-1")
    assert req.id == "gen-1" and req.tenant == "alice"
    # spec() -> parse_request round-trips bit-exactly (the intake journal
    # re-admission contract)
    req2 = parse_request(req.spec(), base, default_id="other")
    assert req2.spec() == req.spec()


def test_request_config_is_one_solo_lane_point():
    base = _base_config()
    req = parse_request({"id": "r", "seed": 259, "origin_rank": 2,
                         "knobs": {"probability_of_rotation": 0.2}},
                        base, default_id="d")
    rc = req.request_config(base)
    assert rc.seed == 259 and rc.origin_rank == 2
    assert rc.num_simulations == 1 and rc.sweep_lanes == 1
    assert rc.checkpoint_path == "" and rc.resume_path == ""
    assert rc.probability_of_rotation == pytest.approx(0.2)
    # untouched geometry: the request cannot change the compile key
    assert rc.num_synthetic_nodes == base.num_synthetic_nodes
    assert rc.gossip_iterations == base.gossip_iterations


# --------------------------------------------------------------------------
# events v2 (serve lifecycle) — v1 logs stay pure and keep validating
# --------------------------------------------------------------------------

def test_serve_events_carry_v2_schema_and_validate():
    rec = get_hub().emit("request_admitted", id="r1", tenant="alice",
                         lane=0)
    assert rec["schema"] == EVENT_SCHEMA_V2
    assert validate_event(rec) == []
    rec = get_hub().emit("journal_commit", unit=0)
    assert rec["schema"] == EVENT_SCHEMA      # non-serve events stay v1
    assert validate_event(rec) == []


def test_v1_schema_is_closed_to_serve_events():
    # a serve event mis-tagged v1 is a bug, not forward compatibility
    bad = {"schema": EVENT_SCHEMA, "seq": 1, "ts": 0.0,
           "ev": "request_admitted", "run": ""}
    assert any("unknown event type" in p for p in validate_event(bad))
    ok = dict(bad, schema=EVENT_SCHEMA_V2)
    assert validate_event(ok) == []
