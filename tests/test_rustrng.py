"""RNG-stack parity: the ChaCha/rand port reproduces the reference's streams.

The golden values here are reference test assertions that depend directly on
the ChaCha20 stream: stake buckets (gossip.rs:1082), and stake stats over
seeded stakes (gossip_stats.rs:2032-2071).
"""

from gossip_sim_tpu.constants import LAMPORTS_PER_SOL
from gossip_sim_tpu.identity import get_stake_bucket, pubkey_new_unique
from gossip_sim_tpu.oracle.rustrng import ChaChaRng

MAX_STAKE = (1 << 20) * LAMPORTS_PER_SOL


def test_seeded_stake_buckets():
    # gossip.rs:1078-1087: 6 draws from seed [189;32] bucket to
    # [15, 16, 19, 19, 20, 20] when sorted by stake.
    rng = ChaChaRng.from_seed_byte(189)
    stakes = [rng.gen_range_u64(1, MAX_STAKE) for _ in range(6)]
    assert [get_stake_bucket(s) for s in sorted(stakes)] == \
        [15, 16, 19, 19, 20, 20]


def test_seeded_stakes_match_stranded_goldens():
    # gossip_stats.rs:2007-2042: stakes drawn for counter-pubkeys 1..10; the
    # four stranded nodes' stakes have these exact stats.
    nodes = [pubkey_new_unique() for _ in range(9)]
    pk = pubkey_new_unique()
    rng = ChaChaRng.from_seed_byte(189)
    stakes = {n.to_string(): rng.gen_range_u64(1, MAX_STAKE) for n in nodes}
    stakes[pk.to_string()] = rng.gen_range_u64(1, MAX_STAKE)
    stranded = [
        "11111113pNDtm61yGF8j2ycAwLEPsuWQXobye5qDR",
        "11111114DhpssPJgSi1YU7hCMfYt1BJ334YgsffXm",
        "11111114d3RrygbPdAtMuFnDmzsN8T5fYKVQ7FVr7",
        "111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT",
    ]
    vals = sorted(stakes[s] for s in stranded)
    assert sum(vals) / 4 == 645017127080371.25
    assert (vals[1] + vals[2]) / 2 == 724161057685112.0
    assert vals[-1] == 1017190976849038
    assert vals[0] == 114555416102223


def test_f64_distribution_range():
    rng = ChaChaRng.from_seed_byte(7)
    vals = [rng.gen_f64() for _ in range(1000)]
    assert all(0.0 <= v < 1.0 for v in vals)
    assert 0.4 < sum(vals) / len(vals) < 0.6


def test_gen_range_bounds():
    rng = ChaChaRng.from_seed_byte(3)
    for _ in range(1000):
        v = rng.gen_range_u64(5, 17)
        assert 5 <= v < 17


def test_u64_straddles_buffer():
    # 63 u32 draws leave one word in the buffer; next_u64 must straddle the
    # refill exactly like rand_core's BlockRng.
    rng = ChaChaRng.from_seed_byte(1)
    first = [rng.next_u32() for _ in range(63)]
    assert len(set(first)) > 32  # sanity: not constant
    v = rng.next_u64()
    rng2 = ChaChaRng.from_seed_byte(1)
    buf1 = [rng2.next_u32() for _ in range(64)]
    lo = buf1[63]
    hi = rng2.next_u32()
    assert v == (hi << 32) | lo
