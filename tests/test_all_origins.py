"""--all-origins product path: sharded batches + full aggregate stats.

The origin-parallel mode is the framework's north-star extension
(SURVEY.md §2.3): every node is an origin, batches shard across the device
mesh ('origins' axis, collective-free), and the full stats suite is computed
from the on-device accumulators instead of per-iteration detail transfers.
"""

import numpy as np
import pytest

from gossip_sim_tpu.cli import run_all_origins
from gossip_sim_tpu.config import Config
from gossip_sim_tpu.identity import pubkey_new_unique


def _accounts(n, seed=0):
    rng = np.random.default_rng(seed)
    return {pubkey_new_unique(): int(s)
            for s in rng.integers(1, 1 << 20, n).astype(np.int64) * 10**9}


def test_all_origins_aggregate_stats_and_mesh():
    accounts = _accounts(48)
    cfg = Config(gossip_iterations=12, warm_up_rounds=8, all_origins=True,
                 origin_batch=16, mesh_devices=8, print_stats=False)
    summary = run_all_origins(cfg, "", accounts=accounts)
    assert summary["mesh_devices"] == 8
    assert summary["num_origins"] == 48
    assert summary["measured_points"] == 4 * 48
    agg = summary["stats"]
    # full suite is populated (VERDICT r4 #3): coverage/RMR/hops/LDH/
    # stranded/branching + message histograms
    assert 0.0 < agg.coverage_stats.mean <= 1.0
    assert agg.rmr_stats.mean > 0
    assert agg.aggregate_hops.max >= agg.aggregate_hops.min >= 1
    assert agg.ldh_stats.max >= agg.ldh_stats.min >= 1
    assert agg.branching_stats.mean > 0
    assert sum(c for _, c in agg.egress_tracker.histogram.items()) > 0
    assert sum(c for _, c in agg.ingress_tracker.histogram.items()) > 0
    # hops histogram counts every measured reached (non-origin) node
    assert agg.hops_hist[1:].sum() > 0 and agg.hops_hist.sum() > 0


def test_all_origins_uneven_final_batch_padding():
    """48 origins, batch 20, mesh 8 -> batches 24/24 (rounded to mesh) with
    the final batch exact; then 50 origins forces a padded final batch whose
    pad columns must not contaminate the aggregates."""
    accounts = _accounts(50, seed=1)
    cfg = Config(gossip_iterations=6, warm_up_rounds=4, all_origins=True,
                 origin_batch=24, mesh_devices=8, print_stats=False)
    summary = run_all_origins(cfg, "", accounts=accounts)
    assert summary["num_origins"] == 50
    assert summary["measured_points"] == 2 * 50


@pytest.mark.slow  # tier-1 budget; tools/sweep_smoke gate covers this
def test_all_origins_tail_batch_padded_to_one_compiled_shape():
    """ISSUE 4: the tail chunk is padded to the full origin_batch width, so
    the whole run compiles at most one batch shape; padded sims are counted
    (``padded_sims``) and masked out of the aggregates — batching 44
    origins as 16+16+12pad4 must agree with one 44-wide batch."""
    from gossip_sim_tpu.engine import compiled_cache_size
    from gossip_sim_tpu.obs import get_registry

    accounts = _accounts(44, seed=7)
    reg = get_registry()
    pad0 = reg.counter("padded_sims")
    cfg = Config(gossip_iterations=6, warm_up_rounds=4, all_origins=True,
                 origin_batch=16, mesh_devices=1, seed=3)
    before = compiled_cache_size()
    chunked = run_all_origins(cfg, "", accounts=accounts)
    delta = compiled_cache_size() - before
    if before >= 0:
        assert delta <= 1, f"tail batch compiled a second shape ({delta})"
    assert reg.counter("padded_sims") - pad0 == 4
    assert chunked["num_origins"] == 44
    assert chunked["measured_points"] == 2 * 44
    assert chunked["padded_sims"] == 4

    whole = run_all_origins(
        Config(gossip_iterations=6, warm_up_rounds=4, all_origins=True,
               origin_batch=44, mesh_devices=1, seed=3),
        "", accounts=accounts)
    # per-origin sims are batch-composition independent (RNG folds the
    # origin id), so only float accumulation order may differ
    assert chunked["coverage_mean"] == pytest.approx(
        whole["coverage_mean"], rel=1e-12)
    assert chunked["rmr_mean"] == pytest.approx(whole["rmr_mean"], rel=1e-12)
    np.testing.assert_array_equal(chunked["stats"].hops_hist,
                                  whole["stats"].hops_hist)


def test_all_origins_single_device_unsharded():
    accounts = _accounts(32, seed=2)
    cfg = Config(gossip_iterations=6, warm_up_rounds=4, all_origins=True,
                 origin_batch=0, mesh_devices=1, print_stats=True)
    summary = run_all_origins(cfg, "", accounts=accounts)
    assert summary["mesh_devices"] == 1
    assert summary["measured_points"] == 2 * 32


def test_all_origins_churn_only_keeps_delivery_stats():
    """Churn alone (no loss, no partition) drops/suppresses nothing, but the
    run is still impaired: the delivery distributions must be populated and
    flagged for output (stats/aggregate.py gates on the config, not on the
    drop totals)."""
    accounts = _accounts(32, seed=5)
    cfg = Config(gossip_iterations=8, warm_up_rounds=4, all_origins=True,
                 origin_batch=0, mesh_devices=1, churn_fail_rate=0.05,
                 churn_recover_rate=0.3, seed=2)
    summary = run_all_origins(cfg, "", accounts=accounts)
    agg = summary["stats"]
    assert agg.impaired
    assert agg.delivered_stats.mean > 0
    # churn holds a nonzero failed population in the aggregate series
    assert agg.failed_stats.mean > 0
    assert agg.total_dropped == 0 and agg.total_suppressed == 0


def test_all_origins_unimpaired_not_flagged():
    accounts = _accounts(24, seed=6)
    cfg = Config(gossip_iterations=6, warm_up_rounds=4, all_origins=True,
                 origin_batch=0, mesh_devices=1)
    summary = run_all_origins(cfg, "", accounts=accounts)
    agg = summary["stats"]
    assert not agg.impaired
    # the engine always emits the (all-zero) counter rows; an unimpaired
    # run must not retain them
    assert agg.delivered_stats.is_empty()
