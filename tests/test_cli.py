"""CLI driver + sweep harness tests (reference: gossip_main.rs:53-241,
279-290, 706-716, 774-951)."""

import numpy as np
import pytest

from gossip_sim_tpu.cli import (build_parser, config_from_args,
                                dispatch_sweeps, find_nth_largest_node,
                                main, run_simulation)
from gossip_sim_tpu.config import Config, StepSize, Testing
from gossip_sim_tpu.identity import pubkey_new_unique
from gossip_sim_tpu.stats.gossip_stats import GossipStatsCollection


def test_default_flags_match_reference():
    """Defaults are the compatibility contract (gossip_main.rs:90-241)."""
    args = build_parser().parse_args([])
    cfg = config_from_args(args)
    assert cfg.gossip_push_fanout == 6
    assert cfg.gossip_active_set_size == 12
    assert cfg.gossip_iterations == 1
    assert cfg.origin_rank == 1
    assert cfg.probability_of_rotation == pytest.approx(0.013333)
    assert cfg.min_ingress_nodes == 2
    assert cfg.prune_stake_threshold == pytest.approx(0.15)
    assert cfg.warm_up_rounds == 200
    assert cfg.num_buckets_for_stranded_node_hist == 10
    assert cfg.num_buckets_for_message_hist == 5
    assert cfg.num_buckets_for_hops_stats_hist == 15
    assert cfg.fraction_to_fail == pytest.approx(0.1)
    assert cfg.when_to_fail == 0
    assert cfg.test_type == Testing.NO_TEST
    assert cfg.num_simulations == 1


def test_probability_validator():
    args = build_parser().parse_args(["--rotation-probability", "1.5"])
    with pytest.raises(SystemExit):
        config_from_args(args)


def test_find_nth_largest_reference_golden():
    """Golden vectors from gossip_main.rs:1056-1069."""
    stakes = [10, 123, 67, 18, 29, 567, 12, 5, 875, 234, 12, 5, 76, 0,
              12354, 985]
    items = [(pubkey_new_unique(), s) for s in stakes]
    for rank, want in zip([5, 10, 12, 1, 6, 2, 9, 16],
                          [234, 18, 12, 12354, 123, 985, 29, 0]):
        assert find_nth_largest_node(rank, items)[1] == want


def _base_config(**kw):
    defaults = dict(gossip_iterations=12, warm_up_rounds=4,
                    gossip_push_fanout=3, num_synthetic_nodes=40,
                    backend="oracle", seed=7)
    defaults.update(kw)
    return Config(**defaults)


def _run(config):
    coll = GossipStatsCollection()
    coll.set_number_of_simulations(config.num_simulations)
    run_simulation(config, "unused", coll, None, 0, "123", 0.0)
    return coll


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_run_simulation_end_to_end(backend):
    coll = _run(_base_config(backend=backend))
    assert len(coll.collection) == 1
    stats = coll.collection[0]
    measured = 12 - 4
    assert len(stats.coverage_stats.collection) == measured
    assert len(stats.rmr_stats.collection) == measured
    assert 0.0 < stats.coverage_stats.mean <= 1.0
    assert stats.rmr_stats.mean >= 0.0
    assert stats.hops_stats.aggregate_stats.max >= 1
    # message counters flowed into the trackers
    assert sum(stats.egress_messages.counts.values()) > 0
    assert sum(stats.ingress_messages.counts.values()) > 0


def test_backend_stats_parity():
    """Same cluster, both backends: pre-prune RMR and coverage agree
    (statistical parity, SURVEY.md §4)."""
    cov, rmr = {}, {}
    for backend in ("oracle", "tpu"):
        coll = _run(_base_config(backend=backend, gossip_iterations=6,
                                 warm_up_rounds=0, gossip_push_fanout=5))
        s = coll.collection[0]
        cov[backend] = s.coverage_stats.mean
        rmr[backend] = s.rmr_stats.collection[0]  # round 0: no prunes yet
    assert cov["oracle"] == pytest.approx(cov["tpu"], abs=0.1)
    # fanout saturated on a full cluster: m = F * n_reached both sides
    assert rmr["oracle"] == pytest.approx(rmr["tpu"], abs=0.35)


def test_sweep_dispatch_steps_parameters(monkeypatch):
    calls = []
    monkeypatch.setattr("gossip_sim_tpu.cli.run_simulation",
                        lambda c, url, coll, q, i, ts, sv: calls.append(c))
    cfg = _base_config(test_type=Testing.PUSH_FANOUT, num_simulations=3,
                       step_size=StepSize(4, True), gossip_push_fanout=6,
                       gossip_active_set_size=12)
    dispatch_sweeps(cfg, "u", [1], GossipStatsCollection(), None, "0")
    assert [c.gossip_push_fanout for c in calls] == [6, 10, 14]
    # fanout > active-set-size bumps the set size (gossip_main.rs:812)
    assert [c.gossip_active_set_size for c in calls] == [12, 12, 14]


def test_sweep_dispatch_origin_rank(monkeypatch):
    calls = []
    monkeypatch.setattr("gossip_sim_tpu.cli.run_simulation",
                        lambda c, url, coll, q, i, ts, sv: calls.append(c))
    cfg = _base_config(test_type=Testing.ORIGIN_RANK, num_simulations=3)
    dispatch_sweeps(cfg, "u", [1, 5, 9], GossipStatsCollection(), None, "0")
    assert [c.origin_rank for c in calls] == [1, 5, 9]


def test_origin_rank_count_validation():
    """Multiple ranks without origin-rank test type is an error
    (gossip_main.rs:713-716)."""
    rc = main(["--origin-rank", "1", "2", "--num-simulations", "2",
               "--num-synthetic-nodes", "10", "--iterations", "1"])
    assert rc == 1


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_fail_nodes_sweep_end_to_end(backend):
    # when_to_fail=0 fires inside the warm-up phase: failed nodes must still
    # be recorded (the TPU warm-up runs as one fused scan)
    cfg = _base_config(backend=backend, test_type=Testing.FAIL_NODES,
                       fraction_to_fail=0.2, when_to_fail=0,
                       gossip_iterations=8, warm_up_rounds=2,
                       step_size=StepSize(0.1, False))
    coll = _run(cfg)
    stats = coll.collection[0]
    assert len(stats.failed_nodes) == int(0.2 * 40)
    # failed nodes are excluded from stranded counts (gossip.rs:334-344)
    stranded = stats.stranded_node_collection.stranded_nodes
    assert not (set(stranded) & stats.failed_nodes)


def test_checkpoint_saved_even_when_all_warmup(tmp_path):
    path = str(tmp_path / "warm.npz")
    cfg = _base_config(backend="tpu", gossip_iterations=3, warm_up_rounds=5,
                       checkpoint_path=path)
    _run(cfg)
    import os
    assert os.path.exists(path)


def test_origin_rank_larger_than_cluster_exits():
    cfg = _base_config(origin_rank=1000)
    with pytest.raises(SystemExit):
        _run(cfg)


def test_e2e_250_nodes_backend_parity():
    """>=200-node end-to-end run on both backends (VERDICT r4 #9): coverage
    saturates and converged RMR agrees statistically at this scale."""
    cov, rmr = {}, {}
    for backend in ("oracle", "tpu"):
        coll = _run(_base_config(backend=backend, num_synthetic_nodes=250,
                                 gossip_push_fanout=6,
                                 gossip_iterations=24, warm_up_rounds=18))
        s = coll.collection[0]
        cov[backend] = s.coverage_stats.mean
        rmr[backend] = s.rmr_stats.mean
    assert cov["oracle"] > 0.97 and cov["tpu"] > 0.97
    assert rmr["oracle"] == pytest.approx(rmr["tpu"], rel=0.15)


def test_origin_rank_sweep_batched_matches_serial():
    """The tpu backend batches ORIGIN_RANK sweeps onto the engine's origin
    axis (one init + one scan).  Every rank's statistics must be
    bit-identical to its own serial single-origin run (per-origin RNG
    streams fold the origin index either way)."""
    from gossip_sim_tpu.cli import dispatch_sweeps
    from gossip_sim_tpu.identity import reset_unique_pubkeys

    ranks = [1, 4, 7]
    base = dict(backend="tpu", num_synthetic_nodes=40, gossip_iterations=24,
                warm_up_rounds=18, gossip_push_fanout=6, seed=9)

    serial = []
    for r in ranks:
        reset_unique_pubkeys()
        coll = GossipStatsCollection()
        run_simulation(_base_config(origin_rank=r, **base), "u", coll,
                       None, 0, "0", 0.0)
        serial.append(coll.collection[0])

    reset_unique_pubkeys()
    coll_b = GossipStatsCollection()
    cfg = _base_config(test_type=Testing.ORIGIN_RANK, num_simulations=3,
                       **base)
    dispatch_sweeps(cfg, "u", ranks, coll_b, None, "0")
    assert len(coll_b.collection) == 3

    for s, b in zip(serial, coll_b.collection):
        assert s.origin == b.origin
        assert s.coverage_stats.collection == b.coverage_stats.collection
        assert s.rmr_stats.collection == b.rmr_stats.collection
        assert (s.hops_stats.raw_hop_collection
                == b.hops_stats.raw_hop_collection)
        assert (s.stranded_node_collection.stranded_nodes
                == b.stranded_node_collection.stranded_nodes)
        assert s.egress_messages.counts == b.egress_messages.counts
        assert s.prune_messages.counts == b.prune_messages.counts


# --------------------------------------------------------------------------
# fault-injection harness (faults.py): flags, sweeps, end-to-end
# --------------------------------------------------------------------------

def test_impairment_flag_validation():
    args = build_parser().parse_args(["--packet-loss-rate", "1.5"])
    with pytest.raises(SystemExit):
        config_from_args(args)
    args = build_parser().parse_args(["--churn-fail-rate", "-0.1"])
    with pytest.raises(SystemExit):
        config_from_args(args)
    args = build_parser().parse_args(
        ["--partition-at", "10", "--heal-at", "5"])
    with pytest.raises(SystemExit):
        config_from_args(args)
    # heal without a partition would emit bogus recovery metrics
    args = build_parser().parse_args(["--heal-at", "5"])
    with pytest.raises(SystemExit):
        config_from_args(args)
    args = build_parser().parse_args(
        ["--packet-loss-rate", "0.1", "--churn-fail-rate", "0.01",
         "--churn-recover-rate", "0.2", "--partition-at", "5",
         "--heal-at", "9", "--test-type", "packet-loss"])
    cfg = config_from_args(args)
    assert cfg.packet_loss_rate == 0.1
    assert cfg.churn_fail_rate == 0.01
    assert cfg.churn_recover_rate == 0.2
    assert cfg.partition_at == 5 and cfg.heal_at == 9
    assert cfg.test_type == Testing.PACKET_LOSS


def test_sweep_dispatch_packet_loss_and_churn(monkeypatch):
    calls = []
    monkeypatch.setattr("gossip_sim_tpu.cli.run_simulation",
                        lambda c, url, coll, q, i, ts, sv: calls.append(c))
    cfg = _base_config(test_type=Testing.PACKET_LOSS, num_simulations=3,
                       step_size=StepSize(0.2, False), packet_loss_rate=0.1)
    dispatch_sweeps(cfg, "u", [1], GossipStatsCollection(), None, "0")
    assert [round(c.packet_loss_rate, 6) for c in calls] == [0.1, 0.3, 0.5]

    calls.clear()
    cfg = _base_config(test_type=Testing.CHURN, num_simulations=3,
                       step_size=StepSize(0.05, False), churn_fail_rate=0.0,
                       churn_recover_rate=0.3)
    dispatch_sweeps(cfg, "u", [1], GossipStatsCollection(), None, "0")
    assert [round(c.churn_fail_rate, 6) for c in calls] == [0.0, 0.05, 0.1]
    # the recover rate rides along unstepped
    assert all(c.churn_recover_rate == 0.3 for c in calls)
    # sweeps clamp at the probability ceiling instead of tripping validation
    calls.clear()
    cfg = _base_config(test_type=Testing.PACKET_LOSS, num_simulations=3,
                       step_size=StepSize(0.6, False), packet_loss_rate=0.0)
    dispatch_sweeps(cfg, "u", [1], GossipStatsCollection(), None, "0")
    assert [round(c.packet_loss_rate, 6) for c in calls] == [0.0, 0.6, 1.0]


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_impaired_run_end_to_end(backend):
    """Loss + churn + partition through run_simulation on both backends:
    degraded-delivery stats flow into the L2 stats layer and the recovery
    metric is computed."""
    cfg = _base_config(backend=backend, packet_loss_rate=0.2,
                       churn_fail_rate=0.05, churn_recover_rate=0.3,
                       partition_at=4, heal_at=8)
    coll = _run(cfg)
    s = coll.collection[0]
    measured = 12 - 4
    assert len(s.delivered_stats.collection) == measured
    assert len(s.failed_count_series) == measured
    assert sum(s.dropped_stats.collection) > 0
    # partition window [4, 8) overlaps measured rounds 4..11
    assert sum(s.suppressed_stats.collection) > 0
    assert s.delivered_stats.mean > 0
    # heal configured -> the recovery metric is always computed
    # (-1 = never recovered within this short run is acceptable)
    assert s.recovery_iterations is not None


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_sweep_baseline_point_still_records_delivery_stats(backend):
    """The rate-0 baseline point of a packet-loss sweep has no impairments
    on, but must still record delivery counters so the sweep's degradation
    trend has an anchor (Config.wants_delivery_stats)."""
    cfg = _base_config(backend=backend, test_type=Testing.PACKET_LOSS,
                       packet_loss_rate=0.0)
    s = _run(cfg).collection[0]
    assert s.has_delivery_stats()
    assert s.delivered_stats.mean > 0
    assert sum(s.dropped_stats.collection) == 0
    assert sum(s.suppressed_stats.collection) == 0


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_recovery_metric_is_iteration_exact_across_warm_up(backend):
    """A heal inside the warm-up window must still be measured on the true
    iteration axis (matching the all-origins aggregate path), not from the
    first measured round.  Partition only, no loss/churn: this small full
    cluster regains coverage 1.0 on the heal iteration itself, so the
    metric must be exactly 0 on both backends."""
    cfg = _base_config(backend=backend, warm_up_rounds=6,
                       partition_at=2, heal_at=4)
    s = _run(cfg).collection[0]
    assert s.recovery_iterations == 0


@pytest.mark.parametrize("backend", ["oracle", "tpu"])
def test_unimpaired_run_has_no_delivery_stats(backend):
    """Reference parity: with every knob off the new stats stay empty."""
    coll = _run(_base_config(backend=backend))
    s = coll.collection[0]
    assert not s.has_delivery_stats()
    assert s.recovery_iterations is None


def test_rc_overflow_warning_reports_percentage(caplog):
    """The rc-overflow warning must quantify the truncation: the count
    alone cannot tell a harmless blip from systematic divergence, so the
    message carries overflow as a percentage of all entries received
    (sum of per-round delivered counts == per-target cache ingress)."""
    import logging

    from gossip_sim_tpu.cli import _warn_shape_truncation
    from gossip_sim_tpu.engine import EngineParams

    params = EngineParams(num_nodes=100)
    rows = {"inb_dropped": np.zeros(3, np.int32),
            "rc_overflow": np.array([3, 4, 0], np.int32),
            "delivered": np.array([100, 150, 100], np.int32)}
    with caplog.at_level(logging.WARNING, logger="gossip_sim_tpu.cli"):
        dropped, overflow = _warn_shape_truncation(rows, params)
    assert (dropped, overflow) == (0, 7)
    msg = "\n".join(r.getMessage() for r in caplog.records)
    assert "7 received-cache entries" in msg
    assert "(2.00% of the 350 entries received)" in msg

    # missing/zero delivered denominator: warn without a bogus percentage
    caplog.clear()
    rows = {"inb_dropped": np.zeros(1, np.int32),
            "rc_overflow": np.array([5], np.int32)}
    with caplog.at_level(logging.WARNING, logger="gossip_sim_tpu.cli"):
        _warn_shape_truncation(rows, params)
    msg = "\n".join(r.getMessage() for r in caplog.records)
    assert "5 received-cache entries" in msg and "%" not in msg
