"""Live telemetry plane (ISSUE 18): hub, event schema, heartbeat
events, exporter endpoints, and the report's telemetry section.

JAX-free by design — obs/telemetry.py and obs/exporter.py must import
and operate without touching an accelerator.
"""
import json
import threading
import time
import urllib.request

import pytest

from gossip_sim_tpu.obs import telemetry
from gossip_sim_tpu.obs.exporter import (PROMETHEUS_CONTENT_TYPE,
                                         TelemetryServer,
                                         parse_prometheus_text,
                                         prometheus_text)
from gossip_sim_tpu.obs.heartbeat import Heartbeat
from gossip_sim_tpu.obs.spans import get_registry
from gossip_sim_tpu.obs.telemetry import (EVENT_SCHEMA, TELEMETRY_SCHEMA,
                                          TelemetryHub, run_key_fingerprint,
                                          validate_event, validate_event_log)


@pytest.fixture(autouse=True)
def _clean_state():
    get_registry().reset()
    telemetry.reset()
    yield
    telemetry.reset()
    get_registry().reset()


# --------------------------------------------------------------------------
# run-key fingerprint (the event-log <-> journal join key)
# --------------------------------------------------------------------------

def test_run_key_fingerprint_stable_and_order_independent():
    a = run_key_fingerprint({"kind": "lane-sweep", "seed": 11, "n": 300})
    b = run_key_fingerprint({"n": 300, "seed": 11, "kind": "lane-sweep"})
    assert a == b
    assert len(a) == 16 and int(a, 16) >= 0
    assert a != run_key_fingerprint({"kind": "lane-sweep", "seed": 12,
                                     "n": 300})


def test_run_key_fingerprint_survives_non_json_values():
    # journal run keys carry enums/StepSize — default=str must cover them
    class Odd:
        def __str__(self):
            return "odd"
    assert run_key_fingerprint({"x": Odd()}) == \
        run_key_fingerprint({"x": Odd()})


# --------------------------------------------------------------------------
# hub: events, ring, file log
# --------------------------------------------------------------------------

def test_emit_assigns_seq_and_carries_fingerprint():
    hub = TelemetryHub()
    fp = hub.set_run_key({"kind": "run"})
    r1 = hub.emit("run_start", pid=1)
    r2 = hub.emit("journal_commit", unit=3)
    assert (r1["seq"], r2["seq"]) == (1, 2)
    assert r1["run"] == r2["run"] == fp
    assert r2["unit"] == 3 and isinstance(r2["unit"], int)
    assert hub.events_emitted() == 2
    assert [e["ev"] for e in hub.recent_events()] == ["run_start",
                                                     "journal_commit"]
    assert validate_event(r1) == [] and validate_event(r2) == []


def test_emit_never_raises_on_bad_payload():
    hub = TelemetryHub()
    # non-int unit would blow int() — emit must swallow, not kill the run
    assert hub.emit("journal_commit", unit="iter") is None


def test_event_log_appends_and_validates(tmp_path):
    path = str(tmp_path / "run.events")
    hub = TelemetryHub()
    hub.set_run_key({"kind": "run"})
    hub.open_event_log(path)
    hub.emit("run_start", pid=7)
    hub.emit("run_end", rc=0)
    hub.close_event_log()
    assert validate_event_log(path) == []
    recs = telemetry.load_event_log(path)
    assert [r["ev"] for r in recs] == ["run_start", "run_end"]
    assert all(r["schema"] == EVENT_SCHEMA for r in recs)


def test_event_log_seq_restart_tolerated_not_regression(tmp_path):
    """A resumed process appends to the same file with seq restarting at
    1 — valid; a seq going sideways mid-run is not."""
    path = str(tmp_path / "resumed.events")
    hub = TelemetryHub()
    hub.open_event_log(path)
    hub.emit("run_start")
    hub.emit("shutdown_signal", signum=15)
    hub.reset()                      # "process" boundary: seq back to 0
    hub.open_event_log(path)         # append mode: same file
    hub.emit("run_start")
    hub.emit("run_end", rc=0)
    hub.close_event_log()
    assert validate_event_log(path) == []
    # corrupt: duplicate a non-1 seq
    with open(path, "a") as f:
        f.write(json.dumps({"schema": EVENT_SCHEMA, "seq": 2, "ts": 1.0,
                            "ev": "run_end", "run": ""}) + "\n")
    assert any("not increasing" in p for p in validate_event_log(path))


def test_validate_event_rejects_junk():
    good = {"schema": EVENT_SCHEMA, "seq": 1, "ts": 1.0, "ev": "run_start",
            "run": ""}
    assert validate_event(good) == []
    assert any("unknown event type" in p
               for p in validate_event({**good, "ev": "made_up"}))
    assert any("missing key" in p
               for p in validate_event({k: v for k, v in good.items()
                                        if k != "ts"}))
    assert any("unknown schema" in p
               for p in validate_event({**good, "schema": "v0"}))
    assert any("unit must be int" in p
               for p in validate_event({**good, "unit": "three"}))
    assert validate_event([]) != []


def test_ring_buffer_bounded():
    hub = TelemetryHub()
    for _ in range(telemetry.RING_DEPTH + 50):
        hub.emit("heartbeat", done=1)
    assert len(hub.recent_events(telemetry.RING_DEPTH * 2)) == \
        telemetry.RING_DEPTH
    assert hub.events_emitted() == telemetry.RING_DEPTH + 50


# --------------------------------------------------------------------------
# heartbeat: every beat feeds the hub; logged ticks become events
# --------------------------------------------------------------------------

def test_heartbeat_state_edge_cases():
    hb = Heartbeat(total_units=0, label="empty")
    st = hb.state(0, now=hb._t0)     # zero-step + zero-elapsed first tick
    assert st["eta_s"] is None and st["rate_per_s"] == 0.0
    assert st["pct"] == 0.0

    hb = Heartbeat(total_units=10, label="loop")
    st = hb.state(15, now=hb._t0 + 1.0)   # overshoot: clamped, raw kept
    assert st["done"] == 10 and st["raw_done"] == 15
    assert st["eta_s"] == 0.0             # finished => ETA 0 always
    st = hb.state(-3, now=hb._t0 + 1.0)
    assert st["done"] == 0 and st["eta_s"] is None

    hb = Heartbeat(total_units=4, label="half")
    st = hb.state(2, now=hb._t0 + 2.0)    # 1 unit/s, 2 left
    assert st["rate_per_s"] == pytest.approx(1.0)
    assert st["eta_s"] == pytest.approx(2.0)


def test_heartbeat_feeds_hub_even_when_log_suppressed():
    hub = telemetry.get_hub()
    hb = Heartbeat(total_units=8, label="quiet", interval_s=3600)
    hb.beat(1)                       # first beat inside the interval
    assert hub.events_emitted() == 0  # suppressed => no event
    snap = hub.snapshot()
    assert snap["progress"]["quiet"]["done"] == 1
    hb.beat(5)
    assert hub.snapshot()["progress"]["quiet"]["done"] == 5


def test_heartbeat_logged_tick_emits_event_with_unit_name():
    hub = telemetry.get_hub()
    hb = Heartbeat(total_units=3, label="sweep", unit="point",
                   interval_s=3600)
    hb.finish()                      # forced tick => logged => event
    evs = hub.recent_events()
    assert [e["ev"] for e in evs] == ["heartbeat"]
    ev = evs[0]
    # "unit" is reserved for int journal unit ids; the name travels apart
    assert "unit" not in ev and ev["unit_name"] == "point"
    assert ev["done"] == ev["total"] == 3 and ev["eta_s"] == 0.0
    assert validate_event(ev) == []


# --------------------------------------------------------------------------
# satellite: live Influx sender stats through the hub
# --------------------------------------------------------------------------

def test_influx_sender_stats_advance_through_live_snapshots():
    from gossip_sim_tpu.sinks.influx import InfluxDB
    db = InfluxDB("http://127.0.0.1:1", "u", "p", "gossip")
    hub = telemetry.get_hub()
    hub.set_provider("influx", db.sender_stats)

    before = hub.snapshot()["influx"]
    assert before["points_sent"] == 0 and before["dropped_points"] == 0
    db.points_sent += 3              # what a 2xx ack does
    db.retry_count += 1
    db._count_dropped()              # no spool path => dropped
    after = hub.snapshot()["influx"]
    assert after["points_sent"] == 3
    assert after["retries"] == 1
    assert after["dropped_points"] == 1
    # and the drop was also a structured event
    assert [e["ev"] for e in hub.recent_events()] == ["influx_drop"]
    # the exporter renders the live numbers, not an end-of-run copy
    metrics = parse_prometheus_text(prometheus_text(hub.snapshot()))
    assert metrics["gossip_sim_influx_points_sent_total"][""] == 3.0
    assert metrics["gossip_sim_influx_retries_total"][""] == 1.0


def test_provider_failure_never_breaks_snapshot():
    hub = telemetry.get_hub()
    hub.set_provider("influx", lambda: 1 / 0)
    assert hub.snapshot()["influx"] == {}
    hub.set_provider("influx", None)     # deregister
    assert hub.snapshot()["influx"] == {}


# --------------------------------------------------------------------------
# satellite: concurrent scrape during mutation — no torn reads
# --------------------------------------------------------------------------

def test_concurrent_snapshot_consistency_under_mutation():
    hub = telemetry.get_hub()
    hub.set_run_key({"kind": "torture"})
    reg = get_registry()
    stop = threading.Event()
    errors = []

    def mutate():
        i = 0
        while not stop.is_set():
            reg.record("engine/rounds", 0.001)
            reg.add("origin_iters", 2)
            hub.emit("heartbeat", done=i)
            hub.note_progress("loop", {"done": i, "total": 10 ** 6})
            i += 1

    def scrape():
        last_seq = 0
        last_oi = 0.0
        last_span = 0
        try:
            for _ in range(300):
                snap = hub.snapshot()
                assert snap["schema"] == TELEMETRY_SCHEMA
                # counters monotone across successive snapshots
                oi = snap["counters"].get("origin_iters", 0)
                assert oi >= last_oi
                last_oi = oi
                seq = snap["events"]["emitted"]
                assert seq >= last_seq
                last_seq = seq
                # no torn span pairs: count monotone, totals coherent
                span = snap["spans"].get("engine/rounds",
                                         {"count": 0, "total_s": 0.0})
                assert span["count"] >= last_span
                last_span = span["count"]
                assert span["total_s"] >= 0.0
                if span["count"]:
                    assert span["total_s"] == pytest.approx(
                        0.001 * span["count"], rel=0.5)
                # the exporter path must render every snapshot strictly
                parse_prometheus_text(prometheus_text(snap))
        except Exception as e:  # surfaced on the main thread below
            errors.append(e)

    writer = threading.Thread(target=mutate)
    reader = threading.Thread(target=scrape)
    writer.start()
    reader.start()
    reader.join(timeout=60)
    stop.set()
    writer.join(timeout=60)
    assert not errors, errors
    assert hub.events_emitted() > 0


# --------------------------------------------------------------------------
# exporter: endpoints + exposition format
# --------------------------------------------------------------------------

def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.headers.get("Content-Type", ""), resp.read()


def test_exporter_serves_metrics_status_events():
    hub = telemetry.get_hub()
    hub.set_run_key({"kind": "run"})
    hub.emit("run_start", pid=1)
    get_registry().add("origin_iters", 42)
    server = TelemetryServer(port=0)
    try:
        port = server.start()
        assert port > 0 and server.running
        base = f"http://127.0.0.1:{port}"
        # the bound port is discoverable from the event ring + registry
        assert [e["ev"] for e in hub.recent_events()][-1] == \
            "telemetry_listen"
        assert get_registry().snapshot()["info"]["telemetry_port"] == port

        ctype, body = _get(base + "/metrics")
        assert ctype == PROMETHEUS_CONTENT_TYPE
        metrics = parse_prometheus_text(body.decode())
        assert metrics["gossip_sim_counter_total"][
            '{counter="origin_iters"}'] == 42.0
        assert metrics["gossip_sim_events_emitted_total"][""] >= 2.0

        ctype, body = _get(base + "/status")
        assert ctype.startswith("application/json")
        status = json.loads(body)
        assert status["schema"] == TELEMETRY_SCHEMA  # default status fn

        _, body = _get(base + "/events?n=1")
        doc = json.loads(body)
        assert doc["schema"] == EVENT_SCHEMA
        assert len(doc["events"]) == 1

        with pytest.raises(urllib.error.HTTPError):
            _get(base + "/nope")
        # scrapes are themselves observable
        assert get_registry().counter("telemetry/scrapes") >= 3
    finally:
        server.stop()
    assert not server.running


def test_exporter_custom_status_fn_and_error_isolation():
    calls = []

    def status_fn():
        calls.append(1)
        if len(calls) > 1:
            raise RuntimeError("mid-run assembly hiccup")
        return {"schema": "custom", "ok": True}

    server = TelemetryServer(port=0, status_fn=status_fn)
    try:
        port = server.start()
        _, body = _get(f"http://127.0.0.1:{port}/status")
        assert json.loads(body)["ok"] is True
        _, body = _get(f"http://127.0.0.1:{port}/status")
        assert "error" in json.loads(body)   # never a dead endpoint
    finally:
        server.stop()


def test_prometheus_text_escapes_and_reparses():
    hub = TelemetryHub()
    hub.note_progress('we"ird\\lab\nel', {"done": 1, "total": 2,
                                          "pct": 50.0, "rate_per_s": 0.5,
                                          "eta_s": None})
    text = prometheus_text(hub.snapshot())
    metrics = parse_prometheus_text(text)    # strict: raises on bad lines
    assert len(metrics["gossip_sim_progress_done"]) == 1
    # eta None renders as the -1 "unknown" sentinel
    assert list(metrics["gossip_sim_progress_eta_seconds"].values()) == [-1.0]


def test_parse_prometheus_text_is_strict():
    with pytest.raises(ValueError):
        parse_prometheus_text("no_value_here\n")
    with pytest.raises(ValueError):
        parse_prometheus_text("bad-name{} 1\n")
    with pytest.raises(ValueError):
        parse_prometheus_text('unterminated{a="b" 1\n')


# --------------------------------------------------------------------------
# run report: the telemetry section
# --------------------------------------------------------------------------

def test_run_report_carries_telemetry_section(tmp_path):
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.obs.report import (REQUIRED_KEYS, build_run_report,
                                           validate_run_report)
    assert "telemetry" in REQUIRED_KEYS
    hub = telemetry.get_hub()
    fp = hub.set_run_key({"kind": "run"})
    hub.open_event_log(str(tmp_path / "r.events"))
    hub.emit("run_start")
    reg = get_registry()
    reg.set_info("telemetry_port", 12345)
    reg.add("telemetry/scrapes", 4)
    report = build_run_report(Config(), reg)
    assert validate_run_report(report) == []
    tel = report["telemetry"]
    assert tel["port"] == 12345
    assert tel["run_fingerprint"] == fp
    assert tel["events_emitted"] == 1
    assert tel["event_log"].endswith("r.events")
    assert tel["scrapes"] == 4
