"""Influx sink tests: line-protocol schema (influx_db.rs:252-603) and the
reporter thread's start/end-sentinel drain loop (influx_db.rs:146-204)."""

import http.server
import threading
import time

from gossip_sim_tpu.sinks import (DatapointQueue, InfluxDataPoint,
                                  InfluxThread)
from gossip_sim_tpu.stats.histogram import Histogram
from gossip_sim_tpu.stats.hops import HopsStat


def test_rmr_line_protocol():
    dp = InfluxDataPoint("1234", 2)
    dp.create_rmr_data_point((2.5, 10, 5))
    assert dp.data().startswith(
        "rmr,simulation_iter=2,start_time=1234 rmr=2.5,m=10,n=5 ")
    assert dp.data().endswith("\n")


def test_generic_data_point():
    dp = InfluxDataPoint("7", 0)
    dp.create_data_point(0.98, "coverage")
    assert dp.data().startswith(
        "coverage,simulation_iter=0,start_time=7 data=0.98 ")


def test_hops_stat_point():
    dp = InfluxDataPoint("7", 1)
    dp.create_hops_stat_point(HopsStat([2, 3, 4]))
    assert dp.data().startswith(
        "hops_stat,simulation_iter=1,start_time=7 mean=3.0,median=3.0,max=4 ")


def test_config_point_fields():
    dp = InfluxDataPoint("9", 0)
    dp.create_config_point(6, 12, 1, 0.15, 2, 0.1, 0.013333)
    line = dp.data()
    for frag in ("config,simulation_iter=0,start_time=9 ", "push_fanout=6",
                 "active_set_size=12", "origin_rank=1",
                 "prune_stake_threshold=0.15", "min_ingress_nodes=2",
                 "fraction_to_fail=0.1", "rotation_probability=0.013333"):
        assert frag in line


def test_iteration_and_sentinels():
    dp = InfluxDataPoint("5", 3)
    dp.create_iteration_point(42, 3)
    assert "iteration,simulation_iter=3,start_time=5 " in dp.data()
    assert "gossip_iter=42,simulation_iter_val=3 " in dp.data()

    start = InfluxDataPoint()
    start.set_start()
    assert start.is_start() and not start.last_datapoint()
    end = InfluxDataPoint()
    end.set_last_datapoint()
    assert end.last_datapoint() and not end.is_start()


def test_histogram_points_emit_one_line_per_bucket():
    h = Histogram()
    h.build(30, 0, 3, [1, 5, 25])
    dp = InfluxDataPoint("11", 0)
    dp.create_histogram_point("aggregate_hops_histogram", h)
    lines = [ln for ln in dp.data().splitlines() if ln]
    assert len(lines) == 3
    assert all(ln.startswith("aggregate_hops_histogram bucket=")
               for ln in lines)

    dp2 = InfluxDataPoint("11", 0)
    dp2.create_messages_point("egress_message_count", h, 4)
    lines2 = [ln for ln in dp2.data().splitlines() if ln]
    assert len(lines2) == 3
    assert all(ln.startswith("egress_message_count,simulation_iter=4,"
                             "start_time=11 bucket=") for ln in lines2)


def test_timestamps_never_collide():
    dp = InfluxDataPoint("1", 0)
    h = Histogram()
    h.build(10, 0, 5, [1, 3, 5, 7, 9])
    dp.create_histogram_point("x", h)
    ts = [int(ln.rsplit(" ", 1)[1]) for ln in dp.data().splitlines() if ln]
    assert len(set(ts)) == len(ts)


class _CapturingHandler(http.server.BaseHTTPRequestHandler):
    received = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        _CapturingHandler.received.append(
            (self.path, body.decode(), self.headers.get("Authorization", "")))
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):
        pass


def test_reporter_thread_posts_and_drains():
    _CapturingHandler.received = []
    server = http.server.HTTPServer(("127.0.0.1", 0), _CapturingHandler)
    port = server.server_address[1]
    srv_thread = threading.Thread(target=server.serve_forever, daemon=True)
    srv_thread.start()
    try:
        q = DatapointQueue()
        start = InfluxDataPoint()
        start.set_start()
        q.push_back(start)
        for i in range(3):
            dp = InfluxDataPoint("77", i)
            dp.create_data_point(float(i), "coverage")
            q.push_back(dp)
        end = InfluxDataPoint()
        end.set_last_datapoint()
        q.push_back(end)

        t = InfluxThread.spawn(f"http://127.0.0.1:{port}", "user", "pass",
                               "testdb", q)
        t.join(timeout=15)
        assert not t.is_alive(), "reporter thread failed to drain and exit"
        assert len(_CapturingHandler.received) == 3
        # POSTs land from per-point sender threads; order is not guaranteed
        bodies = sorted(b for _, b, _ in _CapturingHandler.received)
        assert all(p == "/write?db=testdb"
                   for p, _, _ in _CapturingHandler.received)
        assert bodies[0].startswith(
            "coverage,simulation_iter=0,start_time=77 ")
        assert all(a.startswith("Basic ")
                   for _, _, a in _CapturingHandler.received)
    finally:
        server.shutdown()


def test_reporter_thread_survives_unreachable_endpoint():
    q = DatapointQueue()
    dp = InfluxDataPoint("1", 0)
    dp.create_data_point(1.0, "coverage")
    q.push_back(dp)
    end = InfluxDataPoint()
    end.set_last_datapoint()
    q.push_back(end)
    # port 9 (discard) — connection refused; errors are logged, not raised
    t = InfluxThread.spawn("http://127.0.0.1:9", "u", "p", "db", q)
    t.join(timeout=20)
    assert not t.is_alive()
