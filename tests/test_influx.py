"""Influx sink tests: line-protocol schema (influx_db.rs:252-603) and the
reporter thread's start/end-sentinel drain loop (influx_db.rs:146-204)."""

import http.server
import os
import threading
import time

from gossip_sim_tpu.sinks import (DatapointQueue, InfluxDataPoint,
                                  InfluxThread)
from gossip_sim_tpu.stats.histogram import Histogram
from gossip_sim_tpu.stats.hops import HopsStat


def test_rmr_line_protocol():
    dp = InfluxDataPoint("1234", 2)
    dp.create_rmr_data_point((2.5, 10, 5))
    assert dp.data().startswith(
        "rmr,simulation_iter=2,start_time=1234 rmr=2.5,m=10,n=5 ")
    assert dp.data().endswith("\n")


def test_generic_data_point():
    dp = InfluxDataPoint("7", 0)
    dp.create_data_point(0.98, "coverage")
    assert dp.data().startswith(
        "coverage,simulation_iter=0,start_time=7 data=0.98 ")


def test_hops_stat_point():
    dp = InfluxDataPoint("7", 1)
    dp.create_hops_stat_point(HopsStat([2, 3, 4]))
    assert dp.data().startswith(
        "hops_stat,simulation_iter=1,start_time=7 mean=3.0,median=3.0,max=4 ")


def test_config_point_fields():
    dp = InfluxDataPoint("9", 0)
    dp.create_config_point(6, 12, 1, 0.15, 2, 0.1, 0.013333)
    line = dp.data()
    for frag in ("config,simulation_iter=0,start_time=9 ", "push_fanout=6",
                 "active_set_size=12", "origin_rank=1",
                 "prune_stake_threshold=0.15", "min_ingress_nodes=2",
                 "fraction_to_fail=0.1", "rotation_probability=0.013333"):
        assert frag in line


def test_iteration_and_sentinels():
    dp = InfluxDataPoint("5", 3)
    dp.create_iteration_point(42, 3)
    assert "iteration,simulation_iter=3,start_time=5 " in dp.data()
    assert "gossip_iter=42,simulation_iter_val=3 " in dp.data()

    start = InfluxDataPoint()
    start.set_start()
    assert start.is_start() and not start.last_datapoint()
    end = InfluxDataPoint()
    end.set_last_datapoint()
    assert end.last_datapoint() and not end.is_start()


def test_histogram_points_emit_one_line_per_bucket():
    h = Histogram()
    h.build(30, 0, 3, [1, 5, 25])
    dp = InfluxDataPoint("11", 0)
    dp.create_histogram_point("aggregate_hops_histogram", h)
    lines = [ln for ln in dp.data().splitlines() if ln]
    assert len(lines) == 3
    assert all(ln.startswith("aggregate_hops_histogram bucket=")
               for ln in lines)

    dp2 = InfluxDataPoint("11", 0)
    dp2.create_messages_point("egress_message_count", h, 4)
    lines2 = [ln for ln in dp2.data().splitlines() if ln]
    assert len(lines2) == 3
    assert all(ln.startswith("egress_message_count,simulation_iter=4,"
                             "start_time=11 bucket=") for ln in lines2)


def test_timestamps_never_collide():
    dp = InfluxDataPoint("1", 0)
    h = Histogram()
    h.build(10, 0, 5, [1, 3, 5, 7, 9])
    dp.create_histogram_point("x", h)
    ts = [int(ln.rsplit(" ", 1)[1]) for ln in dp.data().splitlines() if ln]
    assert len(set(ts)) == len(ts)


class _CapturingHandler(http.server.BaseHTTPRequestHandler):
    received = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        _CapturingHandler.received.append(
            (self.path, body.decode(), self.headers.get("Authorization", "")))
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):
        pass


def test_reporter_thread_posts_and_drains():
    _CapturingHandler.received = []
    server = http.server.HTTPServer(("127.0.0.1", 0), _CapturingHandler)
    port = server.server_address[1]
    srv_thread = threading.Thread(target=server.serve_forever, daemon=True)
    srv_thread.start()
    try:
        q = DatapointQueue()
        start = InfluxDataPoint()
        start.set_start()
        q.push_back(start)
        for i in range(3):
            dp = InfluxDataPoint("77", i)
            dp.create_data_point(float(i), "coverage")
            q.push_back(dp)
        end = InfluxDataPoint()
        end.set_last_datapoint()
        q.push_back(end)

        t = InfluxThread.spawn(f"http://127.0.0.1:{port}", "user", "pass",
                               "testdb", q)
        t.join(timeout=15)
        assert not t.is_alive(), "reporter thread failed to drain and exit"
        assert len(_CapturingHandler.received) == 3
        # POSTs land from per-point sender threads; order is not guaranteed
        bodies = sorted(b for _, b, _ in _CapturingHandler.received)
        assert all(p == "/write?db=testdb"
                   for p, _, _ in _CapturingHandler.received)
        assert bodies[0].startswith(
            "coverage,simulation_iter=0,start_time=77 ")
        assert all(a.startswith("Basic ")
                   for _, _, a in _CapturingHandler.received)
    finally:
        server.shutdown()


def test_reporter_thread_survives_unreachable_endpoint():
    q = DatapointQueue()
    dp = InfluxDataPoint("1", 0)
    dp.create_data_point(1.0, "coverage")
    q.push_back(dp)
    end = InfluxDataPoint()
    end.set_last_datapoint()
    q.push_back(end)
    # port 9 (discard) — connection refused; errors are logged, not raised
    t = InfluxThread.spawn("http://127.0.0.1:9", "u", "p", "db", q)
    t.join(timeout=30)
    assert not t.is_alive()


def test_delivery_and_recovery_line_protocol():
    dp = InfluxDataPoint("42", 1)
    dp.create_delivery_point(100, 7, 3, 12)
    dp.create_recovery_point(3, 4.5, 9, 2)
    lines = [ln for ln in dp.data().splitlines() if ln]
    assert lines[0].startswith(
        "delivery,simulation_iter=1,start_time=42 "
        "delivered=100,dropped=7,suppressed=3,failed=12 ")
    assert lines[1].startswith(
        "coverage_recovery,simulation_iter=1,start_time=42 "
        "origins=3,mean_iters=4.5,max_iters=9,unrecovered=2 ")


def test_sim_pull_line_protocol():
    """Pull-phase series (pull.py): request/response/miss/rescue fields."""
    dp = InfluxDataPoint("9", 2)
    dp.create_sim_pull_point(240, 12, 228, 30, 0, 8)
    assert dp.data().startswith(
        "sim_pull,simulation_iter=2,start_time=9 "
        "requests=240,responses=12,misses=228,dropped=30,"
        "suppressed=0,rescued=8 ")


def _start_capture_server():
    _CapturingHandler.received = []
    server = http.server.HTTPServer(("127.0.0.1", 0), _CapturingHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, server.server_address[1]


def test_all_origins_influx_end_to_end():
    """VERDICT r5 #8: run_all_origins(..., dp_queue) through the live HTTP
    harness — the aggregate series (coverage, rmr, hops_stat, stranded,
    message histograms) must arrive on the wire, plus the delivery and
    coverage_recovery series when impairments are configured."""
    import numpy as np

    from gossip_sim_tpu.cli import run_all_origins
    from gossip_sim_tpu.config import Config
    from gossip_sim_tpu.identity import pubkey_new_unique

    rng = np.random.default_rng(9)
    accounts = {pubkey_new_unique(): int(s)
                for s in rng.integers(1, 1 << 20, 32).astype(np.int64)
                * 10**9}
    server, port = _start_capture_server()
    try:
        q = DatapointQueue()
        start = InfluxDataPoint()
        start.set_start()
        q.push_back(start)
        cfg = Config(gossip_iterations=10, warm_up_rounds=4,
                     all_origins=True, origin_batch=16, mesh_devices=1,
                     packet_loss_rate=0.1, partition_at=5, heal_at=7,
                     seed=3, gossip_mode="push-pull", pull_fanout=3)
        summary = run_all_origins(cfg, "", dp_queue=q, start_ts="55",
                                  accounts=accounts)
        assert summary["measured_points"] == 6 * 32
        end = InfluxDataPoint()
        end.set_last_datapoint()
        q.push_back(end)
        t = InfluxThread.spawn(f"http://127.0.0.1:{port}", "u", "p", "db", q)
        t.join(timeout=30)
        assert not t.is_alive(), "reporter failed to drain"
        wire = "".join(b for _, b, _ in _CapturingHandler.received)
        for series in ("coverage,", "rmr,", "hops_stat,",
                       "stranded_node_iterations,",
                       "egress_message_count,", "ingress_message_count,",
                       "prune_message_count,", "delivery,",
                       "coverage_recovery,", "sim_pull,"):
            assert series in wire, f"missing aggregate series {series}"
        # degraded-delivery fields carry the measured loss
        agg = summary["stats"]
        assert agg.total_dropped > 0
        assert f"dropped={agg.dropped_stats.mean}" in wire
        # pull aggregates made it to the wire (ISSUE 5: sim_pull series)
        assert agg.total_pull_requests > 0
        assert f"requests={agg.pull_requests_stats.mean}" in wire
    finally:
        server.shutdown()


class _FlakyHandler(http.server.BaseHTTPRequestHandler):
    failures = 0
    received = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if _FlakyHandler.failures > 0:
            _FlakyHandler.failures -= 1
            self.send_response(500)
            self.end_headers()
            return
        _FlakyHandler.received.append(body.decode())
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):
        pass


def test_post_retries_transient_failures_with_backoff():
    """Two 500s then success: the point must land and count as delivered,
    not dropped."""
    from gossip_sim_tpu.sinks.influx import InfluxDB

    _FlakyHandler.failures = 2
    _FlakyHandler.received = []
    server = http.server.HTTPServer(("127.0.0.1", 0), _FlakyHandler)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        db = InfluxDB(f"http://127.0.0.1:{port}", "u", "p", "db",
                      retry_base=0.01)
        db._post("coverage data=1.0 1\n")
        assert _FlakyHandler.received == ["coverage data=1.0 1\n"]
        assert db.dropped_points == 0
    finally:
        server.shutdown()


def test_post_fails_fast_on_permanent_client_error():
    """4xx (bad auth / malformed body) never succeeds on retry: the point
    drops after ONE attempt instead of burning the full backoff budget."""
    from gossip_sim_tpu.sinks.influx import InfluxDB

    class _Reject400(http.server.BaseHTTPRequestHandler):
        attempts = 0

        def do_POST(self):
            _Reject400.attempts += 1
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(400)
            self.end_headers()

        def log_message(self, *a):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), _Reject400)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        db = InfluxDB(f"http://127.0.0.1:{port}", "u", "p", "db",
                      max_retries=3, retry_base=0.01)
        db._post("coverage data=1.0 1\n")
        assert db.dropped_points == 1
        assert _Reject400.attempts == 1, "4xx must not be retried"
    finally:
        server.shutdown()


def test_post_drops_point_after_retries_exhausted():
    from gossip_sim_tpu.sinks.influx import InfluxDB

    db = InfluxDB("http://127.0.0.1:9", "u", "p", "db",
                  max_retries=1, retry_base=0.01)
    db._post("coverage data=1.0 1\n")
    assert db.dropped_points == 1


def test_retry_exhaustion_spools_point_durably(tmp_path):
    """--influx-spool (ISSUE 7): a retry-exhausted point is appended to
    the on-disk line-protocol spool — original timestamps intact — and
    counted as spooled, not dropped."""
    from gossip_sim_tpu.sinks.influx import InfluxDB

    spool = str(tmp_path / "points.spool")
    db = InfluxDB("http://127.0.0.1:9", "u", "p", "db",
                  max_retries=1, retry_base=0.01, spool_path=spool)
    db._post("coverage data=1.0 123456789\n")
    db._post("rmr rmr=5.0,m=1,n=2 123456790\n")
    stats = db.sender_stats()
    assert stats["spooled_points"] == 2
    assert stats["dropped_points"] == 0
    lines = open(spool).read().splitlines()
    assert lines == ["coverage data=1.0 123456789",
                     "rmr rmr=5.0,m=1,n=2 123456790"]


def test_queue_overflow_spools_and_tracker_converges(tmp_path):
    from gossip_sim_tpu.sinks.influx import InfluxDB, Tracker

    spool = str(tmp_path / "overflow.spool")
    tracker = Tracker()
    db = InfluxDB("http://127.0.0.1:9", "u", "p", "db", tracker=tracker,
                  max_retries=0, retry_base=0.01, max_queue=2,
                  spool_path=spool)
    for i in range(8):
        dp = InfluxDataPoint("1", 0)
        dp.create_data_point(float(i), "coverage")
        db.send_data_points(dp)
        tracker.add_dequeued()
    deadline = time.time() + 30
    while not tracker.equal() and time.time() < deadline:
        time.sleep(0.05)
    assert tracker.equal(), "drain tracker failed to converge"
    stats = db.sender_stats()
    assert stats["spooled_points"] >= 6
    assert stats["dropped_points"] == 0
    assert len(open(spool).read().splitlines()) == stats["spooled_points"]


def test_influx_replay_tool_parses_spool(tmp_path):
    """tools/influx_replay.py --dry-run: counts valid point lines and
    skips a torn final line (killed mid-append)."""
    import subprocess
    import sys as _sys

    spool = tmp_path / "replay.spool"
    spool.write_text("coverage data=1.0 123456789\n"
                     "rmr rmr=5.0,m=1,n=2 123456790\n"
                     "stranded_node_stats count=3 torn-timesta")
    out = subprocess.run(
        [_sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "tools", "influx_replay.py"),
         str(spool), "--dry-run"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "2 point line(s)" in out.stdout
    assert "torn/invalid" in out.stdout


def test_bounded_send_queue_sheds_points_and_tracker_converges():
    """A stalled endpoint must shed overflow points (counted) instead of
    growing the queue without bound — and the drain tracker still converges
    because shed points are marked sent."""
    from gossip_sim_tpu.sinks.influx import InfluxDB, Tracker

    tracker = Tracker()
    db = InfluxDB("http://127.0.0.1:9", "u", "p", "db", tracker=tracker,
                  max_retries=0, retry_base=0.01, max_queue=2)
    for i in range(8):
        dp = InfluxDataPoint("1", 0)
        dp.create_data_point(float(i), "coverage")
        db.send_data_points(dp)
        tracker.add_dequeued()
    deadline = time.time() + 30
    while not tracker.equal() and time.time() < deadline:
        time.sleep(0.05)
    assert tracker.equal(), "drain tracker failed to converge"
    assert db.dropped_points >= 6, "overflow beyond maxsize=2 must be shed"
