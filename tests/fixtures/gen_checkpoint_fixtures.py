"""Generator for the committed v1-v8 checkpoint fixtures (run once).

The fixtures pin the forward-compat contract: every checkpoint format the
project ever shipped must stay loadable by ``load_state`` /
``restore_sim_state`` forever (tests/test_checkpoint.py matrix).  They
are COMMITTED BINARIES — regenerating them with a newer engine would
defeat the point, so this script exists only to document how they were
made (v1-v4: v5-era engine, 2026-08; v5: v6-era engine, 2026-08; v6: the
v7-era engine, 2026-08, with the adaptive direction bit stripped; v7: the
v8-era engine, 2026-08, with the health planes stripped — the push-mode
fixture dynamics are bit-identical between those eras, so each
file is byte-faithful to what its own era's writer produced) and to
rebuild them if the fixture cluster spec itself ever has to change
(requires re-validating against the old loaders).  Existing fixture files
are never overwritten — delete one explicitly to regenerate it.

Each fixture holds:
  * ``state.*``      — SimState arrays after 3 rounds on a 16-node seeded
                       cluster, stripped down to the fields that existed
                       in that format era
  * ``__meta__``     — the era's meta block (format_version, params dict
                       without the fields later eras added)
  * ``fixture.stakes`` — the cluster stakes, so the matrix test can
                       rebuild the exact ClusterTables without depending
                       on the synthetic-account generator's stability

Usage: JAX_PLATFORMS=cpu python tests/fixtures/gen_checkpoint_fixtures.py
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "checkpoints")

# fields each era's SimState did NOT yet have
PRE_V8_MISSING = ("health_prune_recv", "health_first_round")
PRE_V7_MISSING = ("adaptive_pull_on",) + PRE_V8_MISSING
V1_MISSING = ("tfail", "rc_shi", "rc_slo",
              "pull_hops_hist_acc", "pull_rescued_acc") + PRE_V7_MISSING
PRE_V4_MISSING = ("pull_hops_hist_acc", "pull_rescued_acc") + PRE_V7_MISSING
IMPAIR_KEYS = ("packet_loss_rate", "churn_fail_rate", "churn_recover_rate",
               "partition_at", "heal_at", "impair_seed")
PULL_KEYS = ("gossip_mode", "pull_fanout", "pull_interval",
             "pull_bloom_fp_rate", "pull_request_cap", "pull_slots")
# v6 (concurrent traffic) params that did not exist in the v5 era
TRAFFIC_KEYS = ("traffic_values", "traffic_rate", "node_ingress_cap",
                "node_egress_cap", "traffic_stall_rounds")
# v7 (adaptive push-pull) params that did not exist in the v6 era
ADAPTIVE_KEYS = ("adaptive_switch_threshold", "adaptive_switch_hysteresis")
# v8 (node-health observatory) params that did not exist in the v7 era
HEALTH_KEYS = ("health",)


def main():
    import jax
    import jax.numpy as jnp  # noqa: F401 - engine import side effects

    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables, run_rounds)

    os.makedirs(HERE, exist_ok=True)
    rng = np.random.default_rng(42)
    stakes = rng.integers(1, 1 << 16, 16).astype(np.int64) * 1_000_000_000
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=16, warm_up_rounds=0)
    origins = jnp.arange(1, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(7), tables, origins, params)
    state, _ = run_rounds(params, tables, origins, state, 3)
    arrays = {f"state.{f}": np.asarray(getattr(state, f))
              for f in state._fields}
    pdict = dict(params._asdict())

    def write(version, drop_fields, drop_params, meta_extra):
        arrs = {k: v for k, v in arrays.items()
                if k[len("state."):] not in drop_fields}
        p = {k: v for k, v in pdict.items() if k not in drop_params}
        meta = {"format_version": version, "params": p, "iteration": 3}
        meta.update(meta_extra)
        path = os.path.join(HERE, f"v{version}.npz")
        if os.path.exists(path):
            print(f"keep  {path} (committed fixture; delete to regenerate)")
            return
        np.savez_compressed(
            path, __meta__=np.frombuffer(json.dumps(meta).encode(),
                                         dtype=np.uint8),
            **{"fixture.stakes": stakes}, **arrs)
        print(f"wrote {path} ({len(arrs)} state arrays)")

    impair = {k: pdict[k] for k in IMPAIR_KEYS}
    pull = {k: pdict[k] for k in PULL_KEYS if k != "pull_slots"}
    traffic = {k: pdict[k] for k in TRAFFIC_KEYS}
    old = ADAPTIVE_KEYS + HEALTH_KEYS  # params no pre-v7 era ever wrote
    write(1, V1_MISSING, IMPAIR_KEYS + PULL_KEYS + TRAFFIC_KEYS + old, {})
    write(2, PRE_V4_MISSING, IMPAIR_KEYS + PULL_KEYS + TRAFFIC_KEYS + old,
          {})
    write(3, PRE_V4_MISSING, PULL_KEYS + TRAFFIC_KEYS + old,
          {"impair": impair})
    write(4, PRE_V7_MISSING, TRAFFIC_KEYS + old,
          {"impair": impair, "pull": pull})
    # v5: same array set as v4 + the resilience meta block (PR 7); the
    # traffic params of the v6 era do not exist in a v5-era params dict
    write(5, PRE_V7_MISSING, TRAFFIC_KEYS + old,
          {"impair": impair, "pull": pull,
           "resilience": {"journal": "", "committed_units": 0}})
    # v6 (PR 8 era): traffic meta block + kind on every checkpoint; the
    # adaptive direction bit / switch knobs of v7 do not exist yet
    write(6, PRE_V7_MISSING, old,
          {"impair": impair, "pull": pull, "traffic": traffic,
           "resilience": {"journal": "", "committed_units": 0},
           "kind": "sim"})
    # v7 (PR 12 era): adaptive meta block; the health planes / gate of v8
    # do not exist yet
    write(7, PRE_V8_MISSING, HEALTH_KEYS,
          {"impair": impair, "pull": pull, "traffic": traffic,
           "adaptive": {k: pdict[k] for k in ADAPTIVE_KEYS},
           "resilience": {"journal": "", "committed_units": 0},
           "kind": "sim"})
    # v8 (current): the full array set + the health meta block — the
    # gated-off engine carries the health planes as exact zeros
    write(8, (), (),
          {"impair": impair, "pull": pull, "traffic": traffic,
           "adaptive": {k: pdict[k] for k in ADAPTIVE_KEYS},
           "health": {k: pdict[k] for k in HEALTH_KEYS},
           "resilience": {"journal": "", "committed_units": 0},
           "kind": "sim"})


if __name__ == "__main__":
    main()
