"""Node-health observatory tests (ISSUE 17).

Covers the obs/health.py + engine health-plane contract:

* **Digest parity** — the on-device digest (segment-sum deciles, top-k
  hot nodes, exact-integer Gini) is bit-identical to the numpy twin on
  the same integers, including lexsort tie-breaks and i64-range sums.
* **Plane parity** — the engine's gated [N] health accumulators match a
  loop-based ``TrafficOracle`` recount bit-for-bit, in push mode and in
  adaptive mode with prunes + pull rescues actually firing; the slow
  marker carries the 1k-node loss+churn acceptance regime.
* **Gating** — ``--health`` off leaves every non-health output
  bit-identical and every plane identically zero (the planes are carried
  fields, so snapshot shapes never change).
* **Digest invariants** — decile sums equal the cluster aggregate, the
  report section and wire point have their contracted shapes, and the
  ``sim_node_health`` series stays off the deterministic wire surface.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import numpy as np
import pytest

from gossip_sim_tpu.engine import make_cluster_tables
from gossip_sim_tpu.engine.params import EngineParams
from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                           init_traffic_state,
                                           run_traffic_rounds)
from gossip_sim_tpu.obs import health
from gossip_sim_tpu.traffic import TrafficOracle

#: engine TrafficState plane -> TrafficOracle per-round recount field
PLANE_TO_ORACLE = {
    "sent_acc": "node_sent",
    "recv_acc": "node_recv",
    "defer_acc": "node_deferred",
    "qdrop_acc": "node_queue_dropped",
    "prune_acc": "node_prune_sent",
    "health_prune_recv": "node_prune_recv",
    "health_lat_acc": "node_lat_sum",
    "health_del_acc": "node_delivered",
    "health_rescued_acc": "node_rescued",
}

HEALTH_PLANES = ("health_prune_recv", "health_lat_acc", "health_del_acc",
                 "health_rescued_acc")


def _stakes(n, seed=3):
    rng = np.random.default_rng(seed)
    return rng.choice(np.arange(1, 50 * n), size=n,
                      replace=False).astype(np.int64) * 10**6


def _oracle_kwargs(params: EngineParams) -> dict:
    kw = dict(
        impair_seed=params.impair_seed,
        traffic_values=params.traffic_values,
        traffic_rate=params.traffic_rate,
        node_ingress_cap=params.node_ingress_cap,
        node_egress_cap=params.node_egress_cap,
        traffic_stall_rounds=params.traffic_stall_rounds,
        push_fanout=params.push_fanout,
        active_set_size=params.active_set_size,
        min_num_upserts=params.min_num_upserts,
        probability_of_rotation=params.probability_of_rotation,
        packet_loss_rate=params.packet_loss_rate,
        churn_fail_rate=params.churn_fail_rate,
        churn_recover_rate=params.churn_recover_rate)
    if params.gossip_mode == "adaptive":
        kw.update(gossip_mode="adaptive",
                  adaptive_switch_threshold=params.adaptive_switch_threshold,
                  adaptive_switch_hysteresis=params.adaptive_switch_hysteresis)
    return kw


def _run_both(params, stakes, rounds, seed):
    """Engine final state + the oracle's summed per-node recounts."""
    tables = make_cluster_tables(stakes)
    tt = device_traffic_tables(stakes)
    st = init_traffic_state(stakes, params, seed)
    st, _ = run_traffic_rounds(params, tables, tt, st, rounds)

    orc = TrafficOracle(stakes, seed=seed, **_oracle_kwargs(params))
    acc = {f: np.zeros(len(stakes), np.int64) for f in PLANE_TO_ORACLE}
    for it in range(rounds):
        tr = orc.run_round(it)
        for plane, fld in PLANE_TO_ORACLE.items():
            acc[plane] += getattr(tr, fld)
    return st, acc


def _assert_plane_parity(params, stakes, rounds, seed):
    st, acc = _run_both(params, stakes, rounds, seed)
    for plane in PLANE_TO_ORACLE:
        np.testing.assert_array_equal(
            np.asarray(getattr(st, plane)), acc[plane], err_msg=plane)
    return st, acc


# --------------------------------------------------------------------------
# digest math: device vs numpy twin
# --------------------------------------------------------------------------

class TestDigest:
    def test_stake_decile_ids_matches_cluster_tables(self):
        stakes = _stakes(997)
        np.testing.assert_array_equal(
            health.stake_decile_ids(stakes),
            np.asarray(make_cluster_tables(stakes).stake_decile))

    def test_decile_ids_tie_break_by_node_id(self):
        # equal stakes: the stable sort ranks lower node ids first
        ids = health.stake_decile_ids(np.full(20, 7, np.int64))
        np.testing.assert_array_equal(ids, np.arange(20) // 2)

    def test_device_digest_matches_numpy_twin(self):
        rng = np.random.default_rng(11)
        n, p = 1000, 9
        # counts to ~300k: the Gini numerator reaches ~1e12, well past
        # i32 — this is exactly the x64 regime the engine runs in
        stack = rng.integers(0, 300_000, size=(p, n)).astype(np.int64)
        stack[2, 100:110] = stack.max() + 5   # forced hot nodes + ties
        stack[3] = 0                          # degenerate all-zero plane
        ids = health.stake_decile_ids(_stakes(n))
        k = 10
        dv = health.digest_stack(stack, ids, k)
        nv = health.digest_stack_np(stack, ids, k)
        for key in nv:
            np.testing.assert_array_equal(dv[key], nv[key], err_msg=key)

    def test_topk_ties_break_toward_lower_node_id(self):
        idx, val = health.topk_nodes_np(np.array([5, 9, 9, 1, 9]), 3)
        np.testing.assert_array_equal(idx, [1, 2, 4])
        np.testing.assert_array_equal(val, [9, 9, 9])

    def test_gini_known_values(self):
        num, den = health.gini_parts_np(np.full(8, 3))
        assert health.gini_value(num, den) == 0.0       # uniform load
        num, den = health.gini_parts_np([0] * 9 + [90])
        assert health.gini_value(num, den) == pytest.approx(0.9)
        assert health.gini_value(0, 0) == 0.0           # empty plane

    def test_decile_sums_equal_cluster_aggregate(self):
        rng = np.random.default_rng(4)
        plane = rng.integers(0, 1000, 503)
        ids = health.stake_decile_ids(_stakes(503))
        dec = health.decile_sums_np(plane, ids)
        assert dec.sum() == plane.sum()
        assert dec.shape == (health.NUM_DECILES,)


# --------------------------------------------------------------------------
# engine plane parity vs the loop oracle
# --------------------------------------------------------------------------

class TestPlaneParity:
    def test_push_mode_planes_match_oracle(self):
        n = 64
        params = EngineParams(
            num_nodes=n, traffic_values=4, traffic_rate=2,
            node_ingress_cap=6, node_egress_cap=10,
            traffic_stall_rounds=2, warm_up_rounds=0,
            probability_of_rotation=0.2, impair_seed=99,
            packet_loss_rate=0.15, churn_fail_rate=0.03,
            churn_recover_rate=0.3, min_num_upserts=3,
            health=True).validate()
        st, acc = _assert_plane_parity(params, _stakes(n), 10, seed=7)
        assert acc["sent_acc"].sum() > 0
        assert acc["health_del_acc"].sum() > 0

    def test_adaptive_mode_planes_match_oracle_with_rescues(self):
        """Prunes AND pull rescues fire, so the prune-recv / rescued /
        latency planes all take the bursty code paths."""
        n = 120
        params = EngineParams(
            num_nodes=n, warm_up_rounds=0, gossip_mode="adaptive",
            impair_seed=7, adaptive_switch_threshold=0.6,
            adaptive_switch_hysteresis=0.1, traffic_values=6,
            traffic_rate=2, node_ingress_cap=24, node_egress_cap=32,
            traffic_stall_rounds=4, packet_loss_rate=0.1,
            churn_fail_rate=0.02, churn_recover_rate=0.25,
            min_num_upserts=4, health=True).validate()
        st, acc = _assert_plane_parity(params, _stakes(n), 30, seed=11)
        assert acc["prune_acc"].sum() > 0, "regime never pruned"
        assert acc["health_rescued_acc"].sum() > 0, "regime never rescued"
        # rescues are a subset of first deliveries, latencies only exist
        # where deliveries do
        assert (acc["health_rescued_acc"] <= acc["health_del_acc"]).all()
        assert (acc["health_lat_acc"][acc["health_del_acc"] == 0] == 0).all()

    @pytest.mark.slow  # ISSUE 17 acceptance regime; health_smoke covers it
    def test_exact_parity_1k_nodes_under_faults(self):
        n = 1024
        params = EngineParams(
            num_nodes=n, traffic_values=16, traffic_rate=3,
            node_ingress_cap=24, node_egress_cap=48,
            traffic_stall_rounds=3, warm_up_rounds=0,
            probability_of_rotation=0.05, impair_seed=99,
            packet_loss_rate=0.15, churn_fail_rate=0.03,
            churn_recover_rate=0.3, min_num_upserts=5,
            health=True).validate()
        st, acc = _assert_plane_parity(params, _stakes(n), 6, seed=7)
        assert acc["qdrop_acc"].sum() > 0, "no contention in regime"
        # the digest of the real planes also agrees device vs numpy
        ids = health.stake_decile_ids(_stakes(n))
        stack = np.stack([np.asarray(getattr(st, p), np.int64)
                          for p in PLANE_TO_ORACLE])
        dv = health.digest_stack(stack, ids, 10)
        nv = health.digest_stack_np(stack, ids, 10)
        for key in nv:
            np.testing.assert_array_equal(dv[key], nv[key], err_msg=key)


# --------------------------------------------------------------------------
# gating: --health off is bit-identical and all-zero
# --------------------------------------------------------------------------

class TestGating:
    KW = dict(traffic_values=4, traffic_rate=2, node_ingress_cap=6,
              node_egress_cap=10, traffic_stall_rounds=2,
              warm_up_rounds=0, impair_seed=99, packet_loss_rate=0.1,
              churn_fail_rate=0.02, churn_recover_rate=0.3,
              min_num_upserts=3)

    def test_health_is_a_static_compile_key(self):
        on = EngineParams(num_nodes=16, health=True).validate()
        off = EngineParams(num_nodes=16, health=False).validate()
        assert on.static_part() != off.static_part()
        assert on.static_part().health is True

    def test_traffic_gate_off_bit_identical_and_zero_planes(self):
        n = 64
        stakes = _stakes(n)
        tables = make_cluster_tables(stakes)
        tt = device_traffic_tables(stakes)

        def run(health_on):
            p = EngineParams(num_nodes=n, health=health_on,
                             **self.KW).validate()
            st = init_traffic_state(stakes, p, seed=7)
            st, rows = run_traffic_rounds(p, tables, tt, st, 8)
            return st, jax.tree_util.tree_map(np.asarray, rows)

        s_on, r_on = run(True)
        s_off, r_off = run(False)
        assert set(r_on) == set(r_off)
        for k in r_on:
            np.testing.assert_array_equal(r_on[k], r_off[k], err_msg=k)
        for f in s_on._fields:
            if f in HEALTH_PLANES:
                continue
            np.testing.assert_array_equal(
                np.asarray(getattr(s_on, f)), np.asarray(getattr(s_off, f)),
                err_msg=f)
        # gated off, the planes are carried but never incremented
        for f in HEALTH_PLANES:
            assert not np.asarray(getattr(s_off, f)).any(), f
        assert np.asarray(s_on.health_del_acc).sum() > 0

    def test_sim_gate_off_bit_identical_and_zero_planes(self):
        import jax.numpy as jnp

        from gossip_sim_tpu.engine import init_state, run_rounds

        n = 48
        stakes = _stakes(n)
        tables = make_cluster_tables(stakes)
        origins = jnp.arange(2, dtype=jnp.int32)

        def run(health_on):
            p = EngineParams(num_nodes=n, warm_up_rounds=0,
                             min_num_upserts=3, packet_loss_rate=0.1,
                             impair_seed=5, health=health_on).validate()
            st = init_state(jax.random.PRNGKey(3), tables, origins, p)
            st, rows = run_rounds(p, tables, origins, st, 8)
            return st, jax.tree_util.tree_map(np.asarray, rows)

        s_on, r_on = run(True)
        s_off, r_off = run(False)
        for k in r_on:
            np.testing.assert_array_equal(r_on[k], r_off[k], err_msg=k)
        sim_planes = ("health_prune_recv", "health_first_round")
        for f in s_on._fields:
            if f in sim_planes:
                continue
            np.testing.assert_array_equal(
                np.asarray(getattr(s_on, f)), np.asarray(getattr(s_off, f)),
                err_msg=f)
        for f in sim_planes:
            assert not np.asarray(getattr(s_off, f)).any(), f
        # gated on: prune-recv attributes every prune the engine issued
        assert (np.asarray(s_on.health_prune_recv).sum()
                == np.asarray(s_on.prune_acc).sum())
        # first-delivery rounds: origin reached at "round 1" (it 0 + 1),
        # 0 means never reached; any reached node has a positive stamp
        fr = np.asarray(s_on.health_first_round)
        assert fr.max() >= 1
        assert fr.min() >= 0


# --------------------------------------------------------------------------
# report section + wire point
# --------------------------------------------------------------------------

class TestReportAndWire:
    def _digest(self):
        rng = np.random.default_rng(2)
        stack = rng.integers(0, 500, size=(3, 40)).astype(np.int64)
        ids = health.stake_decile_ids(_stakes(40))
        return ("a", "b", "c"), health.digest_stack_np(stack, ids, 5), stack

    def test_section_shape(self):
        names, dig, stack = self._digest()
        sec = health.build_node_health_section(
            names, dig, enabled=True, topk=5, source="engine-traffic")
        assert sec["schema"] == health.HEALTH_SCHEMA
        assert sec["enabled"] and sec["topk"] == 5
        assert set(sec["metrics"]) == set(names)
        m = sec["metrics"]["a"]
        assert m["total"] == int(stack[0].sum())
        assert len(m["deciles"]) == 10 and len(m["hot_nodes"]) == 5
        assert m["hot_nodes"][0]["count"] >= m["hot_nodes"][-1]["count"]
        assert 0.0 <= m["gini"] <= 1.0

    def test_disabled_section_still_validates(self):
        sec = health.build_node_health_section(
            (), None, enabled=False, topk=0, source="")
        assert sec["enabled"] is False and sec["metrics"] == {}

    def test_report_requires_node_health_key(self):
        from gossip_sim_tpu.config import Config
        from gossip_sim_tpu.obs import SpanRegistry
        from gossip_sim_tpu.obs.report import (REQUIRED_KEYS,
                                               build_run_report,
                                               validate_run_report)
        assert "node_health" in REQUIRED_KEYS
        rep = build_run_report(Config(), SpanRegistry())
        assert validate_run_report(rep) == []
        assert rep["node_health"]["enabled"] is False
        bad = dict(rep)
        bad.pop("node_health")
        assert any("node_health" in p for p in validate_run_report(bad))
        # a stamped section rides through verbatim
        reg = SpanRegistry()
        names, dig, _ = self._digest()
        reg.set_info("node_health", health.build_node_health_section(
            names, dig, enabled=True, topk=5, source="engine-traffic"))
        rep2 = build_run_report(Config(), reg)
        assert rep2["node_health"]["enabled"] is True
        assert set(rep2["node_health"]["metrics"]) == set(names)

    def test_influx_point_off_deterministic_wire(self):
        from gossip_sim_tpu.sinks.influx import DatapointQueue, InfluxDataPoint
        names, dig, _ = self._digest()
        vals = health.influx_values(names, dig, topk=5)
        assert vals["a_total"] == int(dig["deciles"][0].sum())
        assert "a_hot0_node" in vals and "c_hot4_count" in vals
        q = DatapointQueue()
        dp = InfluxDataPoint("123", 4)
        dp.create_sim_node_health_point(2, vals)
        dp.create_data_point(1.0, "coverage")
        q.push_back(dp)
        raw = dp.data()
        assert "sim_node_health" in raw and "block=2" in raw
        lines = q.drain_deterministic_lines()
        assert lines and all(not ln.startswith("sim_node_health")
                             for ln in lines)


# --------------------------------------------------------------------------
# kill-and-resume: planes + digests survive a SIGTERM-shaped interrupt
# --------------------------------------------------------------------------

class TestKillAndResume:
    def test_all_origins_resume_health_planes_and_digest_bit_exact(
            self, tmp_path):
        """An all-origins run killed after its first committed batch and
        resumed must land on the same node-health stack (journal-sidecar
        carried) and the identical final digest section as the
        uninterrupted run."""
        from gossip_sim_tpu import resilience
        from gossip_sim_tpu.cli import run_all_origins
        from gossip_sim_tpu.config import Config
        from gossip_sim_tpu.engine import clear_compile_cache
        from gossip_sim_tpu.identity import reset_unique_pubkeys
        from gossip_sim_tpu.obs import get_registry
        from gossip_sim_tpu.resilience import journal_path
        from gossip_sim_tpu.sinks import DatapointQueue

        def cfg(**kw):
            return Config(num_synthetic_nodes=40, gossip_iterations=5,
                          warm_up_rounds=2, all_origins=True,
                          origin_batch=16, seed=9, health=True, **kw)

        def fresh():
            reset_unique_pubkeys()
            get_registry().reset()
            resilience.reset_shutdown()
            clear_compile_cache()

        def section():
            return get_registry().snapshot()["info"]["node_health"]

        try:
            ck_a = str(tmp_path / "full.npz")
            fresh()
            s_a = run_all_origins(cfg(checkpoint_path=ck_a), "",
                                  DatapointQueue(), "0")
            sec_a = section()
            assert sec_a["enabled"] and sec_a["source"] == "all-origins"

            ck = str(tmp_path / "ao.npz")
            fresh()
            resilience.set_kill_after_units(1)   # after batch 0 of 3
            with pytest.raises(resilience.ResumableInterrupt):
                run_all_origins(cfg(checkpoint_path=ck), "",
                                DatapointQueue(), "0")
            assert os.path.exists(journal_path(ck))

            fresh()
            s_c = run_all_origins(cfg(checkpoint_path=ck, resume_path=ck),
                                  "", DatapointQueue(), "0")
            sec_c = section()
        finally:
            resilience.reset_shutdown()

        assert sec_a == sec_c       # deciles, hot nodes, gini — exact
        for k in s_a:
            if k in ("elapsed_s", "origin_iters_per_sec", "stats"):
                continue
            assert s_a[k] == s_c[k], k
        # the sidecar-carried raw stacks themselves agree bit-for-bit
        with np.load(str(tmp_path / "full.aggstate.npz")) as za, \
                np.load(str(tmp_path / "ao.aggstate.npz")) as zc:
            np.testing.assert_array_equal(za["node_health_stack"],
                                          zc["node_health_stack"])

# --------------------------------------------------------------------------
# offline tools: trace_report hot-nodes cross-check + health_report
# --------------------------------------------------------------------------

def _load_tool(name):
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"tools_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTools:
    def _main(self, extra):
        from gossip_sim_tpu.cli import main
        from gossip_sim_tpu.identity import reset_unique_pubkeys
        from gossip_sim_tpu.obs import get_registry
        reset_unique_pubkeys()
        get_registry().reset()
        return main(["--num-synthetic-nodes", "40", "--seed", "7"] + extra)

    def test_trace_report_hot_nodes_cross_checks_sim_planes(
            self, tmp_path, capsys):
        """The trace recount of per-node egress/ingress must equal the
        engine's accumulator planes in the checkpoint bit-for-bit."""
        d, ck = str(tmp_path / "tr"), str(tmp_path / "ck.npz")
        assert self._main(["--iterations", "12", "--warm-up-rounds", "4",
                           "--packet-loss-rate", "0.1",
                           "--trace-dir", d, "--checkpoint-path", ck]) == 0
        trace_report = _load_tool("trace_report")
        rc = trace_report.main(["hot-nodes", d, "--checkpoint", ck])
        out = capsys.readouterr().out
        assert rc == 0
        assert "cross-check egress: OK" in out
        assert "cross-check ingress: OK" in out

    def test_trace_report_hot_nodes_cross_checks_traffic_planes(
            self, tmp_path, capsys):
        d, ck = str(tmp_path / "tr"), str(tmp_path / "ck.npz")
        assert self._main(["--iterations", "12", "--warm-up-rounds", "4",
                           "--traffic-values", "4", "--traffic-rate", "2",
                           "--node-ingress-cap", "4",
                           "--node-egress-cap", "6",
                           "--trace-dir", d, "--checkpoint-path", ck]) == 0
        trace_report = _load_tool("trace_report")
        rc = trace_report.main(["hot-nodes", d, "--checkpoint", ck])
        out = capsys.readouterr().out
        assert rc == 0
        assert "cross-check deferred: OK" in out
        assert "cross-check queue_dropped: OK" in out

    def test_health_report_subcommands_on_real_report(
            self, tmp_path, capsys):
        """hot-nodes conserves the stats queue_dropped total exactly,
        deciles/imbalance render, diff of a report with itself is flat."""
        import json
        rep = str(tmp_path / "rep.json")
        assert self._main(["--iterations", "10", "--warm-up-rounds", "2",
                           "--traffic-values", "4", "--traffic-rate", "2",
                           "--node-ingress-cap", "4",
                           "--node-egress-cap", "6", "--health",
                           "--run-report", rep]) == 0
        health_report = _load_tool("health_report")
        rc = health_report.main(["hot-nodes", rep,
                                 "--metric", "queue_dropped", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        ent = out["queue_dropped"]
        assert ent["conserved"] is True
        assert ent["stats_key"] == "queue_dropped_ingress"
        assert ent["total"] == ent["stats_value"] > 0
        assert sum(e["count"] for e in ent["hot_nodes"]) == ent["listed"]
        # the ranked list is genuinely ranked
        counts = [e["count"] for e in ent["hot_nodes"]]
        assert counts == sorted(counts, reverse=True)

        assert health_report.main(["deciles", rep]) == 0
        assert "mean_latency" in capsys.readouterr().out
        assert health_report.main(["imbalance", rep, "--json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert {r["metric"] for r in rows} >= {"queue_dropped", "deferred"}

        assert health_report.main(["diff", rep, rep, "--json"]) == 0
        d = json.loads(capsys.readouterr().out)
        assert all(v["total_delta"] == 0 and v["gini_delta"] == 0.0
                   for v in d.values())

    def test_health_report_rejects_disabled_section(self, tmp_path):
        rep = str(tmp_path / "rep.json")
        assert self._main(["--iterations", "4", "--run-report", rep]) == 0
        health_report = _load_tool("health_report")
        with pytest.raises(SystemExit, match="disabled"):
            health_report.main(["hot-nodes", rep])
