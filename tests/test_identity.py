"""Identity layer golden tests.

Ports the reference's ``test_get_stake_bucket`` (push_active_set.rs:205-226)
and pins the Pubkey::new_unique/base58 fixture strings used throughout the
reference test suite (gossip_stats.rs:2024-2027 etc.).
"""

import numpy as np

from gossip_sim_tpu.constants import LAMPORTS_PER_SOL
from gossip_sim_tpu.identity import (NodeIndex, Pubkey, b58decode, b58encode,
                                     get_stake_bucket, pubkey_new_unique,
                                     stake_buckets_array)

U64_MAX = (1 << 64) - 1


def test_get_stake_bucket():
    # push_active_set.rs:205-226
    assert get_stake_bucket(0) == 0
    buckets = [0, 1, 2, 2, 3, 3, 3, 3, 4, 4, 4, 4, 4, 4, 4, 4, 5, 5]
    for k, bucket in enumerate(buckets):
        assert get_stake_bucket(k * LAMPORTS_PER_SOL) == bucket
    for stake, bucket in [(4_194_303, 22), (4_194_304, 23),
                          (8_388_607, 23), (8_388_608, 24)]:
        assert get_stake_bucket(stake * LAMPORTS_PER_SOL) == bucket
    assert get_stake_bucket(U64_MAX) == 24


def test_stake_buckets_array_matches_scalar():
    stakes = [0, 1, LAMPORTS_PER_SOL, 17 * LAMPORTS_PER_SOL,
              4_194_304 * LAMPORTS_PER_SOL, U64_MAX]
    arr = stake_buckets_array(np.array(stakes, dtype=np.uint64))
    assert list(arr) == [get_stake_bucket(s) for s in stakes]


def test_pubkey_new_unique_matches_reference_fixtures():
    # Counter values 1..10 produce the exact base58 strings hardcoded in the
    # reference stats tests (gossip_stats.rs:2024-2055).
    got = [pubkey_new_unique().to_string() for _ in range(10)]
    assert got[0] == "1111111QLbz7JHiBTspS962RLKV8GndWFwiEaqKM"
    assert got[6] == "11111113pNDtm61yGF8j2ycAwLEPsuWQXobye5qDR"
    assert got[9] == "111111152P2r5yt6odmBLPsFCLBrFisJ3aS7LqLAT"


def test_base58_roundtrip():
    for _ in range(5):
        pk = pubkey_new_unique()
        assert Pubkey.from_string(pk.to_string()) == pk
    raw = bytes(range(32))
    assert b58decode(b58encode(raw), 32) == raw


def test_node_index_string_order():
    accounts = {pubkey_new_unique(): (i + 1) * LAMPORTS_PER_SOL
                for i in range(20)}
    idx = NodeIndex.from_stakes(accounts)
    strings = [pk.to_string() for pk in idx.pubkeys]
    assert strings == sorted(strings)
    # stakes follow the permutation
    for i, pk in enumerate(idx.pubkeys):
        assert idx.stakes[i] == accounts[pk]
