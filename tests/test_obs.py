"""Observability subsystem tests (gossip_sim_tpu/obs/): span-timer
nesting/overhead, run-report schema, heartbeat/ETA output, the sim_perf
Influx series, and the sender-stats surfacing (ISSUE 2)."""

import json
import threading
import time

import pytest

from gossip_sim_tpu.config import Config
from gossip_sim_tpu.obs import (Heartbeat, SpanRegistry, bench_summary,
                                build_run_report, validate_run_report)
from gossip_sim_tpu.obs.report import REQUIRED_KEYS, RUN_REPORT_SCHEMA
from gossip_sim_tpu.sinks import InfluxDataPoint


# --------------------------------------------------------------------------
# span timers
# --------------------------------------------------------------------------

def test_span_nesting_records_both_levels():
    reg = SpanRegistry()
    with reg.span("outer"):
        time.sleep(0.01)
        with reg.span("inner"):
            time.sleep(0.01)
        assert reg.active_depth() == 1
    assert reg.active_depth() == 0
    assert reg.get("outer") >= reg.get("inner") > 0.0
    assert reg.count("outer") == reg.count("inner") == 1


def test_span_reentrant_same_name():
    reg = SpanRegistry()
    with reg.span("a"):
        with reg.span("a"):
            pass
    assert reg.count("a") == 2


def test_span_accumulates_and_manual_record():
    reg = SpanRegistry()
    for _ in range(3):
        with reg.span("s"):
            pass
    assert reg.count("s") == 3
    reg.record("derived", 1.5, count=10)
    assert reg.get("derived") == pytest.approx(1.5)
    assert reg.count("derived") == 10


def test_counters_info_snapshot_reset():
    reg = SpanRegistry()
    reg.add("origin_iters", 5)
    reg.add("origin_iters", 7)
    reg.set_info("num_nodes", 42)
    with reg.span("x"):
        pass
    snap = reg.snapshot()
    assert snap["counters"]["origin_iters"] == 12
    assert snap["info"]["num_nodes"] == 42
    assert snap["spans"]["x"]["count"] == 1
    assert snap["wall_s"] > 0
    reg.reset()
    assert reg.counter("origin_iters") == 0
    assert reg.get("x") == 0.0
    assert reg.info("num_nodes") is None


def test_span_thread_safety():
    reg = SpanRegistry()
    n_threads, per_thread = 8, 200

    def work():
        for _ in range(per_thread):
            with reg.span("shared"):
                reg.add("hits")

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.count("shared") == n_threads * per_thread
    assert reg.counter("hits") == n_threads * per_thread


def test_spans_concurrent_influx_and_main_not_lost_or_cross_nested():
    """The production concurrency shape (ISSUE 3): the InfluxThread records
    sender spans while the main loop records engine/stats spans.  Nothing
    may be lost (exact counts) and the thread-local span stacks must never
    cross-nest (each thread always sees exactly its own depth)."""
    reg = SpanRegistry()
    n_iters = 400
    barrier = threading.Barrier(2)
    depth_errors = []

    def influx_thread():
        barrier.wait()
        for _ in range(n_iters):
            with reg.span("influx/send"):
                if reg.active_depth() != 1:
                    depth_errors.append(("influx outer", reg.active_depth()))
                with reg.span("influx/retry"):
                    if reg.active_depth() != 2:
                        depth_errors.append(
                            ("influx inner", reg.active_depth()))
                reg.add("points_sent")

    def main_loop():
        barrier.wait()
        for _ in range(n_iters):
            with reg.span("engine/rounds"):
                with reg.span("stats/harvest"):
                    if reg.active_depth() != 2:
                        depth_errors.append(("main inner", reg.active_depth()))
                if reg.active_depth() != 1:
                    depth_errors.append(("main outer", reg.active_depth()))

    t = threading.Thread(target=influx_thread)
    t.start()
    main_loop()
    t.join()
    assert depth_errors == []          # no cross-thread stack bleed
    for name in ("influx/send", "influx/retry", "engine/rounds",
                 "stats/harvest"):
        assert reg.count(name) == n_iters, name   # no lost spans
    assert reg.counter("points_sent") == n_iters
    assert reg.active_depth() == 0
    snap = reg.snapshot()
    assert all(v["total_s"] >= 0 for v in snap["spans"].values())


def test_span_overhead_is_low():
    """The whole point is "cheap enough to leave on": < 50 us per span
    enabled (measured ~1-2 us), and near-free when disabled."""
    reg = SpanRegistry()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with reg.span("hot"):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 50e-6, f"span overhead {per_span*1e6:.1f} us/span"

    off = SpanRegistry(enabled=False)
    t0 = time.perf_counter()
    for _ in range(n):
        with off.span("hot"):
            pass
    per_off = (time.perf_counter() - t0) / n
    assert per_off < 10e-6
    assert off.get("hot") == 0.0


# --------------------------------------------------------------------------
# run report
# --------------------------------------------------------------------------

def _fake_registry():
    reg = SpanRegistry()
    reg.record("ingest", 0.01)
    reg.record("engine/tables", 0.002)
    reg.record("engine/init", 0.5)
    reg.record("engine/compile", 2.0)
    reg.record("engine/rounds", 4.0, count=3)
    reg.record("stats/harvest", 0.1, count=3)
    reg.add("origin_iters", 800)
    reg.add("messages_delivered", 120_000)
    reg.set_info("platform", "cpu")
    reg.set_info("num_nodes", 1000)
    reg.set_info("origin_batch", 8)
    return reg


def test_run_report_schema_golden_keys():
    cfg = Config(gossip_iterations=100, num_synthetic_nodes=1000)
    report = build_run_report(
        cfg, _fake_registry(),
        stats={"coverage_mean": 0.99, "rmr_mean": 5.2},
        influx={"points_sent": 10, "dropped_points": 1, "retries": 2},
        faults={"delivered": 100, "dropped": 3, "suppressed": 0})
    assert validate_run_report(report) == []
    # golden top-level keys: the schema contract
    for key in REQUIRED_KEYS:
        assert key in report, f"missing {key}"
    assert report["schema"] == RUN_REPORT_SCHEMA
    # bench.py-compatible flat fields sourced from the spans
    assert report["init_s"] == pytest.approx(0.5)
    assert report["compile_s"] == pytest.approx(2.0)
    assert report["elapsed_s"] == pytest.approx(4.0)
    assert report["value"] == pytest.approx(800 / 4.0)
    assert report["num_nodes"] == 1000
    assert report["origin_batch"] == 8
    assert report["platform"] == "cpu"
    assert report["coverage_mean"] == pytest.approx(0.99)
    # nested sections
    assert report["throughput"]["messages_per_sec"] == pytest.approx(30000.0)
    assert report["spans"]["engine/rounds"]["count"] == 3
    assert report["influx"]["dropped_points"] == 1
    assert report["faults"]["dropped"] == 3
    assert report["config"]["gossip_iterations"] == 100
    assert report["environment"]["python"]
    # the whole thing must round-trip through JSON
    assert json.loads(json.dumps(report)) == report


def test_validate_run_report_catches_problems():
    cfg = Config()
    report = build_run_report(cfg, _fake_registry())
    assert validate_run_report(report) == []
    bad = dict(report)
    del bad["spans"]
    assert any("spans" in p for p in validate_run_report(bad))
    bad = dict(report)
    bad["value"] = "fast"
    assert any("value" in p for p in validate_run_report(bad))
    bad = dict(report)
    bad["spans"] = {"x": {"total_s": 1.0}}  # no count
    assert any("x" in p for p in validate_run_report(bad))
    assert validate_run_report([]) != []


def test_bench_summary_matches_historical_bench_keys():
    """BENCH trajectory compatibility: bench.py's line keeps its historical
    key set (sourced from the shared spans) plus the ISSUE-4 compile
    accounting (compiles/cache_hits — amortization, not just raw speed)."""
    out = bench_summary(_fake_registry(), platform="cpu", num_nodes=1000,
                        origin_batch=8, iterations=100,
                        coverage_mean=0.994, rmr_mean=5.2)
    assert set(out) == {"metric", "value", "unit", "vs_baseline", "platform",
                        "num_nodes", "origin_batch", "iterations",
                        "elapsed_s", "init_s", "compile_s", "coverage_mean",
                        "rmr_mean", "compiles", "cache_hits"}
    assert out["value"] == pytest.approx(800 / 4.0)
    assert out["compile_s"] == pytest.approx(2.0)


# --------------------------------------------------------------------------
# heartbeat / ETA
# --------------------------------------------------------------------------

def test_heartbeat_logs_rate_and_eta(caplog):
    hb = Heartbeat(100, label="sweep", unit="sim", interval_s=0.0)
    time.sleep(0.01)
    msg = hb.beat(25)
    assert msg is not None
    assert "HEARTBEAT sweep: 25/100" in msg
    assert "(25.0%)" in msg
    assert "ETA" in msg and "?" not in msg.split("ETA")[1]
    assert hb.beats_logged == 1
    final = hb.finish()
    assert "100/100" in final and "(100.0%)" in final


def test_heartbeat_respects_interval():
    hb = Heartbeat(10, interval_s=3600.0)
    assert hb.beat(1) is None          # interval not elapsed
    assert hb.beats_logged == 0
    assert hb.beat(2, force=True) is not None
    assert hb.finish() is not None     # finish always logs


def test_heartbeat_zero_progress_eta_unknown():
    hb = Heartbeat(10, interval_s=0.0)
    msg = hb.beat(0)
    assert "ETA ?" in msg


def test_heartbeat_first_tick_zero_elapsed_no_div_by_zero():
    """A beat fired in the same instant the heartbeat was created (elapsed
    == 0) must not divide by zero and must report ETA '?' — not inf/nan."""
    hb = Heartbeat(10, interval_s=0.0)
    hb._t0 = hb._last = time.monotonic() + 3600.0   # force elapsed <= 0
    msg = hb.beat(0, force=True)
    assert "0/10" in msg and "0.00" in msg and "ETA ?" in msg
    assert "inf" not in msg and "nan" not in msg


def test_heartbeat_single_step_loop():
    """total=1: the first beat is also the last — ETA must be 0:00:00 even
    though no rate is measurable yet, never '?' or negative."""
    hb = Heartbeat(1, interval_s=0.0)
    msg = hb.beat(1, force=True)
    assert "1/1" in msg and "(100.0%)" in msg and "ETA 0:00:00" in msg
    assert hb.finish() is not None


def test_heartbeat_done_clamped_to_total():
    """done beyond total (a caller overshooting the unit count) clamps
    instead of reporting >100% or a negative ETA."""
    hb = Heartbeat(4, interval_s=0.0)
    time.sleep(0.01)
    msg = hb.beat(9)
    assert "4/4" in msg and "(100.0%)" in msg and "ETA 0:00:00" in msg


def test_heartbeat_zero_total_never_crashes():
    hb = Heartbeat(0, interval_s=0.0)
    msg = hb.finish()
    assert "0/0" in msg and "ETA ?" in msg


# --------------------------------------------------------------------------
# sim_perf series + sender stats
# --------------------------------------------------------------------------

def test_sim_perf_line_protocol():
    dp = InfluxDataPoint("99", 2)
    dp.create_sim_perf_point(0.251, 1020.5, 7, 256)
    assert dp.data().startswith(
        "sim_perf,simulation_iter=2,start_time=99 "
        "round_wall_s=0.251,origin_iters_per_sec=1020.5,"
        "queue_depth=7,iters=256 ")
    assert dp.data().endswith("\n")


def test_influx_thread_exposes_sender_stats_after_drain():
    from gossip_sim_tpu.sinks import DatapointQueue, InfluxThread

    q = DatapointQueue()
    dp = InfluxDataPoint("1", 0)
    dp.create_data_point(1.0, "coverage")
    q.push_back(dp)
    end = InfluxDataPoint()
    end.set_last_datapoint()
    q.push_back(end)
    t = InfluxThread.spawn("http://127.0.0.1:9", "u", "p", "db", q)
    t.join(timeout=30)
    assert not t.is_alive()
    stats = t.sender_stats()
    assert stats["dropped_points"] == 1
    assert stats["points_sent"] == 0
    assert stats["retries"] >= 1
    assert set(stats) == {"points_sent", "dropped_points",
                          "spooled_points", "retries"}


# --------------------------------------------------------------------------
# XProf stage annotations
# --------------------------------------------------------------------------

def test_round_step_named_scopes_reach_compiled_hlo():
    """The round/* named scopes must survive into compiled-HLO op metadata
    — that is what XProf/TensorBoard groups device time by.  With default
    (all-off) impairment knobs the fault scopes are python-gated out."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables)
    from gossip_sim_tpu.engine.core import round_step

    stakes = (np.arange(1, 21) * 10**9).astype(np.int64)
    tables = make_cluster_tables(stakes)
    params = EngineParams(num_nodes=20, warm_up_rounds=0)
    origins = jnp.arange(1, dtype=jnp.int32)
    state = init_state(jax.random.PRNGKey(0), tables, origins, params)
    comp = jax.jit(
        lambda st: round_step(params, tables, origins, st, jnp.int32(0))
    ).lower(state).compile()
    hlo = comp.as_text()
    for scope in ("round/verb1_push_targets", "round/bfs_propagate",
                  "round/verb2_consume", "round/rc_merge",
                  "round/verb3_prune_decide", "round/verb4_prune_apply",
                  "round/verb5_rotate", "round/round_stats"):
        assert scope in hlo, f"named scope {scope} missing from HLO"


# --------------------------------------------------------------------------
# CLI integration: flags + end-to-end run report
# --------------------------------------------------------------------------

def test_profile_dir_flag_and_alias():
    from gossip_sim_tpu.cli import build_parser, config_from_args

    cfg = config_from_args(build_parser().parse_args(
        ["--profile-dir", "/tmp/x"]))
    assert cfg.jax_profile_dir == "/tmp/x"
    cfg = config_from_args(build_parser().parse_args(
        ["--jax-profile", "/tmp/y"]))  # historical alias still accepted
    assert cfg.jax_profile_dir == "/tmp/y"
    cfg = config_from_args(build_parser().parse_args(
        ["--run-report", "/tmp/r.json"]))
    assert cfg.run_report_path == "/tmp/r.json"


def test_cli_run_report_end_to_end_tpu(tmp_path):
    """Acceptance: a default CPU run with --run-report emits schema-valid
    JSON with nonzero compile/round/stats spans and throughput."""
    from gossip_sim_tpu.cli import main

    path = str(tmp_path / "report.json")
    rc = main(["--num-synthetic-nodes", "30", "--iterations", "10",
               "--warm-up-rounds", "4", "--seed", "7",
               "--run-report", path])
    assert rc == 0
    with open(path) as f:
        report = json.load(f)
    assert validate_run_report(report) == []
    assert report["num_nodes"] == 30
    assert report["origin_batch"] == 1
    assert report["spans"]["engine/compile"]["total_s"] > 0
    assert report["spans"]["engine/rounds"]["total_s"] > 0
    assert report["spans"]["stats/harvest"]["total_s"] > 0
    assert report["spans"]["engine/init"]["total_s"] > 0
    assert report["throughput"]["origin_iters_per_sec"] > 0
    assert report["counters"]["origin_iters"] == 6
    assert 0.0 < report["coverage_mean"] <= 1.0
    assert report["config"]["num_synthetic_nodes"] == 30
    assert report["environment"]["jax_version"]


def test_cli_run_report_oracle_backend(tmp_path):
    from gossip_sim_tpu.cli import main

    path = str(tmp_path / "report.json")
    rc = main(["--num-synthetic-nodes", "20", "--iterations", "6",
               "--warm-up-rounds", "2", "--seed", "3", "--backend", "oracle",
               "--run-report", path])
    assert rc == 0
    with open(path) as f:
        report = json.load(f)
    assert validate_run_report(report) == []
    assert report["platform"] == "oracle"
    assert report["spans"]["engine/rounds"]["total_s"] > 0
    assert report["spans"]["stats/harvest"]["total_s"] > 0
    assert report["counters"]["origin_iters"] == 4
    assert report["value"] > 0
