"""Exact port of the reference's received-cache golden test
(received_cache.rs:141-200)."""

from gossip_sim_tpu.identity import pubkey_new_unique
from gossip_sim_tpu.oracle.received_cache import ReceivedCache


def test_received_cache():
    cache = ReceivedCache(capacity=100)
    pubkey = pubkey_new_unique()
    origin = pubkey_new_unique()
    records = [
        [3, 1, 7, 5],
        [7, 6, 5, 2],
        [2, 0, 0, 2],
        [3, 5, 0, 6],
        [6, 2, 6, 2],
    ]
    nodes = [pubkey_new_unique() for _ in records]
    for node, recs in zip(nodes, records):
        for num_dups, k in enumerate(recs):
            for _ in range(k):
                cache.record(origin, node, num_dups)

    entry = cache.cache[origin]
    assert entry.num_upserts == 21
    expected_scores = {nodes[0]: 4, nodes[1]: 13, nodes[2]: 2,
                       nodes[3]: 8, nodes[4]: 8}
    assert entry.nodes == expected_scores

    stakes = {nodes[0]: 6, nodes[1]: 1, nodes[2]: 5, nodes[3]: 3,
              nodes[4]: 7, pubkey: 9, origin: 9}

    # First prune on a copy-equivalent: rebuild an identical cache.
    cache2 = ReceivedCache(capacity=100)
    for node, recs in zip(nodes, records):
        for num_dups, k in enumerate(recs):
            for _ in range(k):
                cache2.record(origin, node, num_dups)
    got = set(cache2.prune(pubkey, origin, 0.5, 2, stakes))
    assert got == {nodes[0], nodes[2], nodes[3]}

    got = set(cache.prune(pubkey, origin, 1.0, 0, stakes))
    assert got == {nodes[0], nodes[2]}


def test_prune_resets_entry_state():
    # The gate consumes the entry (mem::take, received_cache.rs:55): after a
    # successful prune, scores and upserts restart from zero.
    cache = ReceivedCache(capacity=10)
    pubkey = pubkey_new_unique()
    origin = pubkey_new_unique()
    peer = pubkey_new_unique()
    stakes = {pubkey: 100, origin: 100, peer: 1}
    for _ in range(20):
        cache.record(origin, peer, 0)
    assert cache.cache[origin].num_upserts == 20
    cache.prune(pubkey, origin, 0.0, 0, stakes)
    assert cache.cache[origin].num_upserts == 0
    assert cache.cache[origin].nodes == {}


def test_prune_gate_below_threshold():
    cache = ReceivedCache(capacity=10)
    pubkey = pubkey_new_unique()
    origin = pubkey_new_unique()
    peer = pubkey_new_unique()
    stakes = {pubkey: 100, origin: 100, peer: 1}
    for _ in range(19):
        cache.record(origin, peer, 0)
    assert cache.prune(pubkey, origin, 0.0, 0, stakes) == []
    assert cache.cache[origin].num_upserts == 19  # untouched


def test_capacity_gate_for_late_messages():
    # num_dups >= 2 inserts only while under capacity 50
    # (received_cache.rs:91-97); timely messages always insert.
    cache = ReceivedCache(capacity=10)
    origin = pubkey_new_unique()
    late_peers = [pubkey_new_unique() for _ in range(60)]
    for p in late_peers:
        cache.record(origin, p, 5)
    assert len(cache.cache[origin].nodes) == 50
    timely = pubkey_new_unique()
    cache.record(origin, timely, 1)
    assert len(cache.cache[origin].nodes) == 51
    assert cache.cache[origin].nodes[timely] == 1
