"""Benchmark: full five-verb gossip rounds, 10k-node cluster, batched origins.

Prints ONE JSON line:
  {"metric": "origin_iters_per_sec", "value": ..., "unit": "origin*iters/s",
   "vs_baseline": ...}

Baseline context (BASELINE.md): the north-star target is 10k nodes x ALL
origins x 1000 iterations in < 60 s on a v5e-8 — i.e. 166,667 origin-iters/s
across 8 chips, 20,833 per chip.  ``vs_baseline`` is measured single-chip
throughput over that per-chip share (>= 1.0 means the 8-chip target is met
by origin-parallel scaling, which is collective-free).

Armored (round-5): the accelerator backend in this environment can hang or
fail at init, so every JAX touch happens in a *subprocess* with a hard
timeout.  The parent probes the backend (with retries), then walks a falling
shape ladder until a rung completes; if the accelerator never comes up it
falls back to a small CPU run so a number is always printed.  Diagnostics
(probe errors, failed rungs, versions) ride along in the JSON.

The worker's init_s/compile_s/elapsed_s come from the shared obs span
registry (gossip_sim_tpu/obs/) — the same spans ``--run-report`` emits —
so BENCH trajectory lines and product run reports are directly comparable.
Two sweep rungs ride along: ``sweep_steps_per_sec`` (serial warm-executable
sweep steps, ISSUE 4) and ``lane_sweep_steps_per_sec`` (the same per-point
work as one lane-batched device program, engine/lanes.py / ISSUE 6 —
their ratio is the lane amortization factor the 10x ROADMAP target is
about).
A slow-waking TPU gets more than one probe window via
``GOSSIP_BENCH_PROBE_TIMEOUT`` (seconds per attempt, default 150) and
``GOSSIP_BENCH_PROBE_TRIES`` (attempts, default 3) — but a probe that
*hangs* to the hard timeout is not retried, and the failure is cached on
disk (``GOSSIP_BENCH_PROBE_CACHE``, TTL ``GOSSIP_BENCH_PROBE_CACHE_TTL``)
so an unavailable accelerator costs one timeout per cache window instead
of three per run.
"""

import argparse
import json
import os
import subprocess
import sys
import time

from gossip_sim_tpu.obs import PER_CHIP_TARGET  # noqa: F401 (re-export)

# (num_nodes, origin_batch, iterations, per-rung timeout seconds)
LADDER = [
    (10_000, 32, 100, 900),
    (4_000, 16, 100, 600),
    (1_000, 8, 50, 420),
]
# 1500 s: the rung ran 596 s of the old 600 s budget in BENCH_r08; the
# ISSUE-13 capacity harvest adds one extra XLA compile per executable,
# and two rungs landed since — health (ISSUE 17, ~a second traffic run)
# and the n=10k sparse rung (ISSUE 19, one extra compile + timed rounds)
CPU_RUNG = (1_000, 4, 20, 1500)


def _env_number(name, default, cast):
    try:
        return cast(os.environ.get(name, ""))
    except (TypeError, ValueError):
        return default


PROBE_TIMEOUT = max(1.0, _env_number("GOSSIP_BENCH_PROBE_TIMEOUT", 150,
                                     float))
PROBE_RETRIES = max(1, _env_number("GOSSIP_BENCH_PROBE_TRIES", 3, int))


def synthetic_stakes(n, seed=0):
    """Heavy-tailed mainnet-like stake distribution (lognormal, ~5 orders of
    magnitude spread like the real validator set)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    sol = np.exp(rng.normal(9.5, 2.0, n)).astype(np.int64) + 1
    return sol * 1_000_000_000


# --------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess; prints one JSON line)
# --------------------------------------------------------------------------

def worker(args) -> int:
    import numpy as np
    import jax

    if os.environ.get("GOSSIP_BENCH_FORCE_CPU"):
        # Some environments force-register an accelerator PJRT plugin via
        # sitecustomize and pin jax_platforms past the env var; override at
        # the config level before any backend initializes.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gossip_sim_tpu.engine import (EngineParams, enable_persistent_cache,
                                       init_state, make_cluster_tables,
                                       persistent_cache_counters,
                                       persistent_cache_dir, run_rounds)
    from gossip_sim_tpu.obs import bench_summary, get_registry
    from gossip_sim_tpu.obs import capacity, memwatch

    # capacity observatory (ISSUE 13): harvest XLA cost/memory analysis
    # per compiled executable so every rung line carries a measured
    # memory baseline (ROADMAP item 1's "memory-per-node tracked in
    # BENCH").  The harvest's one extra AOT compile per executable fires
    # INSIDE run_rounds (obs/capacity.py hook), i.e. inside this
    # worker's timed sections — every timed figure below therefore
    # subtracts the harvest-compile seconds accrued in its window
    # (reg "capacity/harvest_compile"), so compile_s / elapsed_s /
    # first-call numbers stay comparable with pre-harvest BENCH rounds.
    capacity.reset_harvests()
    capacity.set_harvest_enabled(True)

    def harvest_s() -> float:
        return reg.get("capacity/harvest_compile")

    def deduct_harvest(span: str, h0: float) -> None:
        """Remove harvest-compile seconds accrued since ``h0`` from a
        span total (count 0: the call count stays honest)."""
        dh = harvest_s() - h0
        if dh > 0:
            reg.record(span, -dh, count=0)

    def rung_capacity(p, site, origin_batch=1, lanes=0):
        """peak RSS + ledger bytes/node + the XLA temp/output bytes of
        the executables harvested so far at ``site``."""
        led = capacity.capacity_ledger(p, origin_batch=origin_batch,
                                       lanes=lanes)
        peaks = capacity.site_peaks(site)
        return {
            "peak_rss_bytes": memwatch.peak_rss_bytes(),
            "mem_bytes_per_node": led["bytes_per_node"],
            "ledger_total_bytes": led["total_bytes"],
            "ledger_state_bytes": led["state_bytes"],
            "xla_temp_bytes": peaks["temp_bytes"],
            "xla_output_bytes": peaks["output_bytes"],
            "xla_argument_bytes": peaks["argument_bytes"],
        }

    # persistent XLA compilation cache (engine/cache.py): repeat BENCH runs
    # with GOSSIP_COMPILATION_CACHE set reuse the compiled round across
    # processes, and the hit/miss counts ride along in the JSON line.  A
    # broken cache dir must not kill the rung (the armored-bench contract:
    # a number is always printed) — run uncached instead.
    try:
        enable_persistent_cache()
    except Exception as e:
        print(f"persistent compilation cache unavailable ({e}); "
              f"running uncached", file=sys.stderr)

    platform = jax.devices()[0].platform
    n, o = args.num_nodes, args.origin_batch
    tables = make_cluster_tables(synthetic_stakes(n))
    params = EngineParams(num_nodes=n, warm_up_rounds=0)
    origins = jnp.arange(o, dtype=jnp.int32)

    # the shared span names (obs/report.py conventions) make this line
    # field-for-field comparable with a --run-report from a product run
    reg = get_registry()
    reg.reset()
    with reg.span("engine/init"):
        state = init_state(jax.random.PRNGKey(0), tables, origins, params)
        jax.block_until_ready(state)

    # compile + protocol warm-up (also brings the prune/rotate paths live)
    h0 = harvest_s()
    with reg.span("engine/compile"):
        state, rows = run_rounds(params, tables, origins, state,
                                 args.warmup_timing)
        jax.block_until_ready(rows)
    deduct_harvest("engine/compile", h0)

    h0 = harvest_s()
    with reg.span("engine/rounds"):
        state, rows = run_rounds(params, tables, origins, state,
                                 args.iterations, start_it=args.warmup_timing)
        jax.block_until_ready(rows)
    deduct_harvest("engine/rounds", h0)
    reg.add("origin_iters", o * args.iterations)
    coverage_mean = float(np.asarray(rows["coverage"]).mean())
    rmr_mean = float(np.asarray(rows["rmr"]).mean())
    main_capacity = rung_capacity(params, "engine/run_rounds",
                                  origin_batch=o)

    # ---- sweep rung: warm-executable sweep throughput ------------------
    # Steps a numeric EngineKnobs field per simulated point (the sweep
    # harness pattern, gossip_main.rs:774-951).  Step 0 compiles the
    # sweep-block shape once; the timed steps 1..K then measure pure
    # compile-free sweep throughput — the amortization the dynamic-knob
    # split buys (sweep cost = compile + K*run, not K*(compile+run)).
    from gossip_sim_tpu.engine import compiled_cache_size
    sweep_steps = args.sweep_steps
    sweep_iters = max(1, min(10, args.iterations))
    it_at = args.warmup_timing + args.iterations

    def sweep_params(k):
        return params._replace(
            probability_of_rotation=0.013333 + 1e-4 * (k + 1))

    state, srows = run_rounds(sweep_params(0), tables, origins, state,
                              sweep_iters, start_it=it_at)
    jax.block_until_ready(srows["coverage"])
    it_at += sweep_iters
    c_before = compiled_cache_size()
    h0 = harvest_s()
    t_sweep = time.perf_counter()
    for k in range(1, sweep_steps + 1):
        state, srows = run_rounds(sweep_params(k), tables, origins, state,
                                  sweep_iters, start_it=it_at)
        jax.block_until_ready(srows["coverage"])
        it_at += sweep_iters
    sweep_dt = time.perf_counter() - t_sweep - (harvest_s() - h0)
    sweep_compiles = (compiled_cache_size() - c_before
                      if c_before >= 0 else -1)
    sweep_capacity = rung_capacity(params, "engine/run_rounds",
                                   origin_batch=o)

    # ---- lane rung: the sweep axis as ONE batched device program -------
    # (engine/lanes.py, ISSUE 6).  Same per-point work as the serial sweep
    # rung above — sweep_iters rounds at the same (n, o) — but all lanes
    # execute inside one compiled call, so the two rungs' steps/sec are
    # directly comparable: lane_sweep_steps_per_sec / sweep_steps_per_sec
    # is the lane amortization factor (the 10x ROADMAP target is an
    # accelerator number; a compute-bound CPU sees ~1x minus vmap
    # overhead, which this rung tracks honestly).
    from gossip_sim_tpu.engine import (broadcast_state, lane_cache_size,
                                       run_rounds_lanes, stack_knobs)
    lanes = max(1, args.lane_sweep_lanes)
    static = params.static_part()
    lane_knobs = stack_knobs([sweep_params(k).knob_values()
                              for k in range(1, lanes + 1)])
    h0 = harvest_s()
    t_lc = time.perf_counter()
    lstates, lrows = run_rounds_lanes(
        static, tables, origins, broadcast_state(state, lanes), lane_knobs,
        sweep_iters, start_it=it_at)
    jax.block_until_ready(lrows["coverage"])
    lane_compile_dt = time.perf_counter() - t_lc - (harvest_s() - h0)
    c_warm = lane_cache_size()
    h0 = harvest_s()
    t_lane = time.perf_counter()
    lstates, lrows = run_rounds_lanes(
        static, tables, origins, broadcast_state(state, lanes), lane_knobs,
        sweep_iters, start_it=it_at)
    jax.block_until_ready(lrows["coverage"])
    lane_dt = time.perf_counter() - t_lane - (harvest_s() - h0)
    lane_compiles = (lane_cache_size() - c_warm if c_warm >= 0 else -1)
    lane_capacity = rung_capacity(params, "engine/run_rounds_lanes",
                                  origin_batch=o, lanes=lanes)

    # ---- traffic rung: M concurrent values on one shared network -------
    # (traffic.py / engine/traffic.py, ISSUE 10).  M=64 in-flight values
    # at n<=1000 under both queue caps — the heavy-traffic workload the
    # ROADMAP's "millions of users" north star asks about.  Records round
    # throughput AND values-converged/s (the number that actually matters
    # for a traffic workload: how fast the network finishes values).
    from gossip_sim_tpu.engine.traffic import (device_traffic_tables,
                                               init_traffic_state,
                                               run_traffic_rounds)
    tn = min(n, 1_000)
    tstakes = synthetic_stakes(tn)
    ttables_c = make_cluster_tables(tstakes) if tn != n else tables
    # caps sized for *measurable* contention: tight enough that queue
    # deferrals/drops are nonzero, loose enough that values still finish
    # inside the timed window (values-converged/s must not read 0 on a
    # healthy engine)
    tparams = EngineParams(
        num_nodes=tn, warm_up_rounds=0, traffic_values=64, traffic_rate=4,
        node_ingress_cap=256, node_egress_cap=384, traffic_stall_rounds=4)
    tt = device_traffic_tables(tstakes)
    titers = max(5, min(20, args.iterations))
    tstate = init_traffic_state(tstakes, tparams, seed=0)
    h0 = harvest_s()
    t_tc = time.perf_counter()
    tstate, trows = run_traffic_rounds(tparams, ttables_c, tt, tstate, 3)
    jax.block_until_ready(trows["converged"])
    traffic_compile_dt = time.perf_counter() - t_tc - (harvest_s() - h0)
    h0 = harvest_s()
    t_tr = time.perf_counter()
    tstate, trows = run_traffic_rounds(tparams, ttables_c, tt, tstate,
                                       titers, start_it=3)
    jax.block_until_ready(trows["converged"])
    traffic_dt = time.perf_counter() - t_tr - (harvest_s() - h0)
    traffic_converged = int(np.asarray(trows["converged"]).sum())
    traffic_retired = int(np.asarray(trows["retired"]).sum())
    _rm = np.asarray(trows["ret_mask"])
    traffic_ret_cov = (float(np.asarray(trows["ret_holders"])[_rm].sum()
                             / (tn * max(traffic_retired, 1)))
                       if traffic_retired else 0.0)
    # captured BEFORE the adaptive rung compiles, so these XLA bytes are
    # the push-traffic executables alone
    traffic_capacity = rung_capacity(tparams, "engine/run_traffic_rounds")

    # ---- adaptive traffic rung: the same starved workload healed by the
    # direction-optimizing switch (adaptive.py, ISSUE 11).  Identical
    # config + seed as the traffic rung with --gossip-mode adaptive, so
    # the values_converged / values_rescued deltas vs push are the
    # robustness number: BENCH_r07's push arm converges 0 of 80 values at
    # ~98.7% coverage; the per-value pull-rescue phase finishes them.
    aparams = tparams._replace(gossip_mode="adaptive")
    astate = init_traffic_state(tstakes, aparams, seed=0)
    h0 = harvest_s()
    t_ac = time.perf_counter()
    astate, arows = run_traffic_rounds(aparams, ttables_c, tt, astate, 3)
    jax.block_until_ready(arows["converged"])
    adaptive_compile_dt = time.perf_counter() - t_ac - (harvest_s() - h0)
    h0 = harvest_s()
    t_ar = time.perf_counter()
    astate, arows = run_traffic_rounds(aparams, ttables_c, tt, astate,
                                       titers, start_it=3)
    jax.block_until_ready(arows["converged"])
    adaptive_dt = time.perf_counter() - t_ar - (harvest_s() - h0)
    a_conv = int(np.asarray(arows["converged"]).sum())
    a_ret = int(np.asarray(arows["retired"]).sum())
    _am = np.asarray(arows["ret_mask"])
    a_nodes_rescued = int(np.asarray(arows["ret_rescued"])[_am].sum())
    a_vals_rescued = int(np.count_nonzero(
        np.asarray(arows["ret_rescued"])[_am]
        * np.asarray(arows["ret_full"])[_am]))
    # site peaks now include the adaptive executables (max over both
    # traffic statics — the adaptive graph is the larger of the two)
    adaptive_capacity = rung_capacity(aparams, "engine/run_traffic_rounds")

    # ---- health rung: the traffic workload with the node-health planes
    # accumulating (obs/health.py, ISSUE 17).  Identical config + seed as
    # the traffic rung with health=True, so the warm-elapsed delta IS the
    # plane-accumulation cost; health_overhead_pct is the number
    # tools/bench_trend.py tracks (and tools/health_smoke.py bounds <2%).
    hparams = tparams._replace(health=True)
    hstate = init_traffic_state(tstakes, hparams, seed=0)
    h0 = harvest_s()
    t_hc = time.perf_counter()
    hstate, hrows = run_traffic_rounds(hparams, ttables_c, tt, hstate, 3)
    jax.block_until_ready(hrows["converged"])
    health_compile_dt = time.perf_counter() - t_hc - (harvest_s() - h0)
    h0 = harvest_s()
    t_hr = time.perf_counter()
    hstate, hrows = run_traffic_rounds(hparams, ttables_c, tt, hstate,
                                       titers, start_it=3)
    jax.block_until_ready(hrows["converged"])
    health_dt = time.perf_counter() - t_hr - (harvest_s() - h0)
    # one end-of-rung digest dispatch, timed (the per-block host harvest
    # is [10,·]/[k,·] only — this is the whole observability hot path)
    from gossip_sim_tpu.obs import health as health_obs
    hstack = jnp.stack([hstate.sent_acc, hstate.recv_acc, hstate.defer_acc,
                        hstate.qdrop_acc, hstate.health_del_acc])
    t_dg = time.perf_counter()
    hdig = health_obs.digest_stack(hstack, ttables_c.stake_decile, 10)
    digest_dt = time.perf_counter() - t_dg

    # ---- sparse rung: the frontier representation past the dense wall --
    # (engine/sparse.py, ISSUE 19).  Always runs at n=10,000 — the first
    # size beyond the dense all-origins 16GB ceiling (~3.9k nodes) —
    # regardless of the ladder rung, because that is the point of the
    # representation: the rc stake planes leave SimState (derived from
    # the cluster tables each round) and routing goes through the
    # segment-reduce frontier kernels.  The per-round math is bit-exact
    # vs dense (tools/sparse_smoke.py gates that), so steps/sec here is
    # a pure representation-cost number, and the ledger bytes/node is
    # the figure capacity_report.py --representation sparse projects.
    sn, so = 10_000, o
    sparse_iters = max(1, min(10, args.iterations))
    sparams = EngineParams(num_nodes=sn, warm_up_rounds=0,
                           representation="sparse").validate()
    stables = make_cluster_tables(synthetic_stakes(sn))
    sorigins = jnp.arange(so, dtype=jnp.int32)
    sstate = init_state(jax.random.PRNGKey(0), stables, sorigins, sparams)
    h0 = harvest_s()
    t_sc = time.perf_counter()
    sstate, sprows = run_rounds(sparams, stables, sorigins, sstate, 3)
    jax.block_until_ready(sprows["coverage"])
    sparse_compile_dt = time.perf_counter() - t_sc - (harvest_s() - h0)
    h0 = harvest_s()
    t_sr = time.perf_counter()
    sstate, sprows = run_rounds(sparams, stables, sorigins, sstate,
                                sparse_iters, start_it=3)
    jax.block_until_ready(sprows["coverage"])
    sparse_dt = time.perf_counter() - t_sr - (harvest_s() - h0)
    sparse_cov = float(np.asarray(sprows["coverage"]).mean())
    # site peaks at engine/run_rounds now include the sparse executables;
    # at 10x the dense rung's N the maxima are the sparse graph's
    sparse_capacity = rung_capacity(sparams, "engine/run_rounds",
                                    origin_batch=so)

    # ---- serve rung: continuous-batching request throughput ------------
    # (serve/, ISSUE 20).  K dynamically-membered lanes stream a queue of
    # scenario requests through the ONE warm dyn-lane executable the
    # --serve daemon holds: each request runs sweep_iters rounds in
    # blocks, and a lane splices the next queued request the block after
    # its current one finishes — exactly the daemon's block-boundary
    # admission.  requests/sec here is the device-plane serve throughput
    # (host-side stats harvest + HTTP ride on top in the live daemon;
    # tools/serve_smoke.py gates that plane's correctness bit-for-bit).
    from gossip_sim_tpu.engine import (dyn_lane_cache_size,
                                       run_rounds_lanes_dyn,
                                       splice_lane_state, stack_origins)
    vn = tn                       # n<=1000, same cluster as traffic rung
    vparams = EngineParams(num_nodes=vn, warm_up_rounds=0).validate()
    vtables = ttables_c if tn == vn else make_cluster_tables(tstakes)
    vstatic = vparams.static_part()
    klanes = 4
    vreqs = 3 * klanes
    vblock = next(b for b in range(min(5, sweep_iters), 0, -1)
                  if sweep_iters % b == 0)

    def _req_init(i):
        # per-request identity: own seed, origin, and a traced knob value
        knobs = vparams._replace(
            probability_of_rotation=0.013333 + 1e-4 * (i + 1))
        org = jnp.asarray([i % vn], jnp.int32)
        st = init_state(jax.random.PRNGKey(i), vtables, org, knobs)
        return knobs.knob_values(), org, st

    def _serve_stream():
        lane_req = list(range(klanes))       # request index per lane
        lane_done = [0] * klanes             # rounds done per lane
        inits = [_req_init(i) for i in range(klanes)]
        lane_kvals = [kv for kv, _, _ in inits]   # per-lane knob tuples
        lane_orgs = [org for _, org, _ in inits]  # per-lane origin rows
        kstack = stack_knobs(lane_kvals)
        ostack = stack_origins(lane_orgs)
        states = broadcast_state(inits[0][2], klanes)
        for k in range(1, klanes):
            states = splice_lane_state(states, k, inits[k][2])
        next_req, completed = klanes, 0
        while completed < vreqs:
            states, vrows = run_rounds_lanes_dyn(
                vstatic, vtables, ostack, states, kstack, vblock,
                start_its=jnp.asarray(lane_done, jnp.int32))
            jax.block_until_ready(vrows["coverage"])
            for k in range(klanes):
                if lane_req[k] < 0:
                    continue
                lane_done[k] += vblock
                if lane_done[k] < sweep_iters:
                    continue
                completed += 1
                if next_req < vreqs:         # splice the next request in
                    kv, org, st = _req_init(next_req)
                    lane_req[k], next_req = next_req, next_req + 1
                    lane_done[k] = 0
                    lane_kvals[k], lane_orgs[k] = kv, org
                    kstack = stack_knobs(lane_kvals)
                    ostack = stack_origins(lane_orgs)
                    states = splice_lane_state(states, k, st)
                else:                        # idle lane keeps stepping
                    lane_req[k] = -1
        return completed

    h0 = harvest_s()
    t_vc = time.perf_counter()
    _serve_stream()                          # cold: dyn kernel compiles
    serve_compile_dt = time.perf_counter() - t_vc - (harvest_s() - h0)
    c_warm = dyn_lane_cache_size()
    h0 = harvest_s()
    t_vr = time.perf_counter()
    serve_completed = _serve_stream()        # warm: the daemon's regime
    serve_dt = time.perf_counter() - t_vr - (harvest_s() - h0)
    serve_compiles = (dyn_lane_cache_size() - c_warm
                      if c_warm >= 0 else -1)
    serve_capacity = rung_capacity(vparams, "engine/run_rounds_lanes_dyn",
                                   lanes=klanes)

    result = bench_summary(
        reg, platform=platform, num_nodes=n, origin_batch=o,
        iterations=args.iterations,
        coverage_mean=coverage_mean, rmr_mean=rmr_mean)
    result["sweep_steps_per_sec"] = round(
        sweep_steps / sweep_dt, 2) if sweep_dt > 0 else 0.0
    result["sweep"] = {
        "steps": sweep_steps,
        "iters_per_step": sweep_iters,
        "warm_steps_elapsed_s": round(sweep_dt, 3),
        "compiles_during_warm_steps": sweep_compiles,
        **sweep_capacity,
    }
    result["lane_sweep_steps_per_sec"] = round(
        lanes / lane_dt, 2) if lane_dt > 0 else 0.0
    result["lane_sweep"] = {
        "lanes": lanes,
        "iters_per_step": sweep_iters,
        "warm_elapsed_s": round(lane_dt, 3),
        "first_call_elapsed_s": round(lane_compile_dt, 3),
        "compiles_during_warm_steps": lane_compiles,
        "vs_serial_sweep": (round((lanes / lane_dt) /
                                  (sweep_steps / sweep_dt), 3)
                            if lane_dt > 0 and sweep_dt > 0
                            and sweep_steps else 0.0),
        **lane_capacity,
    }
    result["traffic_steps_per_sec"] = round(
        titers / traffic_dt, 2) if traffic_dt > 0 else 0.0
    result["traffic"] = {
        "num_nodes": tn,
        "traffic_values": tparams.traffic_values,
        "traffic_rate": tparams.traffic_rate,
        "node_ingress_cap": tparams.node_ingress_cap,
        "node_egress_cap": tparams.node_egress_cap,
        "timed_rounds": titers,
        "warm_elapsed_s": round(traffic_dt, 3),
        "first_call_elapsed_s": round(traffic_compile_dt, 3),
        "values_converged": traffic_converged,
        "values_retired": traffic_retired,
        "values_converged_per_sec": (round(traffic_converged / traffic_dt, 2)
                                     if traffic_dt > 0 else 0.0),
        "values_retired_per_sec": (round(traffic_retired / traffic_dt, 2)
                                   if traffic_dt > 0 else 0.0),
        "retired_coverage_mean": round(traffic_ret_cov, 4),
        "injected": int(np.asarray(trows["injected"]).sum()),
        "queue_dropped": int(np.asarray(trows["queue_dropped"]).sum()),
        "deferred": int(np.asarray(trows["deferred"]).sum()),
        **traffic_capacity,
    }
    result["adaptive_traffic_steps_per_sec"] = round(
        titers / adaptive_dt, 2) if adaptive_dt > 0 else 0.0
    result["adaptive_traffic"] = {
        "gossip_mode": "adaptive",
        "adaptive_switch_threshold": aparams.adaptive_switch_threshold,
        "adaptive_switch_hysteresis": aparams.adaptive_switch_hysteresis,
        "timed_rounds": titers,
        "warm_elapsed_s": round(adaptive_dt, 3),
        "first_call_elapsed_s": round(adaptive_compile_dt, 3),
        "values_converged": a_conv,
        "values_retired": a_ret,
        "values_rescued": a_vals_rescued,
        "nodes_rescued": a_nodes_rescued,
        "switched_to_pull": int(np.asarray(
            arows["switched_to_pull"]).sum()),
        "pull_sent": int(np.asarray(arows["pull_sent"]).sum()),
        "pull_responses": int(np.asarray(arows["pull_responses"]).sum()),
        "queue_dropped": int(np.asarray(arows["queue_dropped"]).sum()),
        # the robustness deltas vs the push arm above (same config+seed)
        "delta_vs_push": {
            "values_converged": a_conv - traffic_converged,
            "values_rescued": a_vals_rescued,
            "values_retired": a_ret - traffic_retired,
        },
        **adaptive_capacity,
    }
    result["health_overhead_pct"] = round(
        100.0 * (health_dt - traffic_dt) / traffic_dt, 2) \
        if traffic_dt > 0 else 0.0
    result["health"] = {
        "timed_rounds": titers,
        "warm_elapsed_s": round(health_dt, 3),
        "first_call_elapsed_s": round(health_compile_dt, 3),
        "digest_s": round(digest_dt, 4),
        "queue_dropped_total": int(np.asarray(hstate.qdrop_acc).sum()),
        "queue_dropped_gini": health_obs.gini_value(
            int(hdig["gini_num"][3]), int(hdig["gini_den"][3])),
    }
    result["sparse_steps_per_sec"] = round(
        sparse_iters / sparse_dt, 2) if sparse_dt > 0 else 0.0
    result["sparse"] = {
        "num_nodes": sn,
        "origin_batch": so,
        "timed_rounds": sparse_iters,
        "warm_elapsed_s": round(sparse_dt, 3),
        "first_call_elapsed_s": round(sparse_compile_dt, 3),
        "coverage_mean": round(sparse_cov, 4),
        **sparse_capacity,
    }
    result["serve_requests_per_sec"] = round(
        serve_completed / serve_dt, 3) if serve_dt > 0 else 0.0
    result["serve"] = {
        "num_nodes": vn,
        "lanes": klanes,
        "requests": vreqs,
        "rounds_per_request": sweep_iters,
        "block_rounds": vblock,
        "warm_elapsed_s": round(serve_dt, 3),
        "first_call_elapsed_s": round(serve_compile_dt, 3),
        "compiles_during_stream": serve_compiles,
        **serve_capacity,
    }
    # run-level capacity line (ROADMAP item 1's measured memory baseline;
    # tools/bench_trend.py tracks these across rounds)
    hs = capacity.harvest_summary()
    result["capacity"] = {
        **main_capacity,
        # the run-level peak is read HERE, after every rung: VmHWM is
        # monotone and the traffic/adaptive rungs allocate ~3x the main
        # rung (main_capacity's own peak key is the main-rung snapshot)
        "peak_rss_bytes": memwatch.peak_rss_bytes(),
        "xla_peak_temp_bytes": hs["peak_temp_bytes"],
        "xla_flops": hs["flops"],
        "cost_harvests": hs["harvests"],
        "cost_harvest_failures": hs["failures"],
        # total AOT harvest-compile seconds (deducted from every timed
        # figure above — see the worker preamble)
        "harvest_compile_s": round(harvest_s(), 3),
    }
    pc = persistent_cache_counters()
    result["compilation_cache"] = {
        "dir": persistent_cache_dir() or "",
        "hits": pc["hits"], "misses": pc["misses"],
    }
    print(json.dumps(result))
    return 0


# --------------------------------------------------------------------------
# parent: probe + ladder orchestration, every JAX touch subprocessed
# --------------------------------------------------------------------------

def _run_sub(cmd, timeout, env=None):
    """Run ``cmd`` with a hard timeout; returns (rc, stdout, stderr_tail)."""
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, env=env,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
        return p.returncode, p.stdout, p.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        err = (e.stderr or b"")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        return -9, "", f"TIMEOUT after {timeout}s; stderr tail: {err[-1500:]}"


def _probe_cache_path():
    """Failed-probe cache file (``GOSSIP_BENCH_PROBE_CACHE``; "0"/"off"
    disables, unset = a stable per-user temp path)."""
    import tempfile
    v = os.environ.get("GOSSIP_BENCH_PROBE_CACHE", "")
    if v.lower() in ("0", "off", "none"):
        return None
    if v:
        return v
    return os.path.join(tempfile.gettempdir(),
                        f"gossip-sim-probe-cache-{os.getuid()}.json")


PROBE_CACHE_TTL = max(0.0, _env_number("GOSSIP_BENCH_PROBE_CACHE_TTL",
                                       1800.0, float))


def _read_probe_cache():
    """-> (age_seconds, failure_reason) of a cached probe FAILURE, or
    None.  The reason is whatever diagnostic the failing probe recorded
    (timeout tail, error text) so a CPU-fallback BENCH line can say WHY
    it is a CPU line instead of silently reporting CPU numbers."""
    path = _probe_cache_path()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            entry = json.load(f)
        age = time.time() - float(entry["ts"])
        reason = str(entry.get("reason", "unknown"))
    except (OSError, ValueError, KeyError, TypeError):
        return None
    return (age, reason) if 0 <= age < PROBE_CACHE_TTL else None


def _write_probe_cache(reason: str = ""):
    path = _probe_cache_path()
    if not path:
        return
    try:
        with open(path, "w") as f:
            json.dump({"ts": time.time(), "platform": None,
                       "reason": reason[-500:]}, f)
    except OSError:
        pass


def probe_backend():
    """Ask a subprocess what jax.devices() says.

    Failure handling (round-6: an unavailable TPU must cost ONE timeout,
    not PROBE_RETRIES of them — BENCH_r05 burned 3 x 150 s on a hung
    backend before falling back to CPU):

    * a probe that HANGS (hard timeout) is not retried — a backend that
      cannot answer ``jax.devices()`` in PROBE_TIMEOUT seconds will not be
      healed by a 10 s pause; fast non-timeout errors still get the full
      retry budget;
    * the failure is cached on disk (``GOSSIP_BENCH_PROBE_CACHE``, TTL
      ``GOSSIP_BENCH_PROBE_CACHE_TTL`` = 1800 s) so repeat bench
      invocations inside the window skip the probe entirely and go
      straight to the CPU fallback rung.  Successes are never cached — a
      freshly-revived accelerator is always picked up.

    Returns (platform_or_None, diagnostics list, cached_failure_or_None);
    the third element is ``{"age_s":..., "reason":...}`` exactly when the
    probe was skipped because of a cached failure — main() stamps it into
    the BENCH json (``probe_cached_failure``) so CPU-fallback numbers are
    never silent about why they are CPU numbers."""
    code = ("import jax, json; d = jax.devices(); "
            "print(json.dumps({'platform': d[0].platform, 'n': len(d), "
            "'version': jax.__version__}))")
    diags = []
    cached = _read_probe_cache()
    if cached is not None:
        age, reason = cached
        diags.append(
            f"probe skipped: cached failure {round(age)}s ago "
            f"(< ttl {round(PROBE_CACHE_TTL)}s; delete "
            f"{_probe_cache_path()} or set GOSSIP_BENCH_PROBE_CACHE=off "
            f"to force a probe)")
        return None, diags, {"age_s": round(age, 1), "reason": reason}
    last_err = ""
    for attempt in range(PROBE_RETRIES):
        t0 = time.time()
        rc, out, err = _run_sub([sys.executable, "-c", code], PROBE_TIMEOUT)
        dt = round(time.time() - t0, 1)
        if rc == 0 and out.strip():
            try:
                info = json.loads(out.strip().splitlines()[-1])
                diags.append(f"probe[{attempt}] ok in {dt}s: {info}")
                return info["platform"], diags, None
            except (ValueError, KeyError) as e:
                diags.append(f"probe[{attempt}] unparseable ({e}): {out[:200]}")
                last_err = f"unparseable probe output: {out[:200]}"
        else:
            diags.append(f"probe[{attempt}] rc={rc} in {dt}s: {err[-300:]}")
            last_err = f"rc={rc} in {dt}s: {err[-300:]}"
        if rc == -9:
            diags.append("probe hung to the hard timeout; not retrying "
                         "(a hung backend does not heal in seconds)")
            break
        if attempt < PROBE_RETRIES - 1:
            time.sleep(min(10 * (attempt + 1), 30))
    _write_probe_cache(last_err)
    return None, diags, None


def run_rung(n, o, iters, warmup, tmo, env, diags, label="", lanes=32):
    """Spawn one worker rung; returns its parsed JSON or None."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--num-nodes", str(n), "--origin-batch", str(o),
           "--iterations", str(iters), "--warmup-timing", str(warmup),
           "--lane-sweep-lanes", str(lanes)]
    t0 = time.time()
    rc, out, err = _run_sub(cmd, tmo, env=env)
    dt = round(time.time() - t0, 1)
    tag = f"rung{label} n={n} o={o}"
    if rc == 0 and out.strip():
        try:
            result = json.loads(out.strip().splitlines()[-1])
            diags.append(f"{tag} ok in {dt}s")
            return result
        except ValueError:
            diags.append(f"{tag}: unparseable stdout {out[:200]}")
    else:
        diags.append(f"{tag} rc={rc} in {dt}s: {err[-400:]}")
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-nodes", type=int, default=0,
                    help="fix the rung instead of walking the ladder")
    ap.add_argument("--origin-batch", type=int, default=32)
    ap.add_argument("--iterations", type=int, default=100)
    ap.add_argument("--warmup-timing", type=int, default=5)
    ap.add_argument("--sweep-steps", type=int, default=3,
                    help="warm-executable sweep steps timed for the "
                         "sweep_steps_per_sec rung")
    ap.add_argument("--lane-sweep-lanes", type=int, default=32,
                    help="lanes for the lane_sweep_steps_per_sec rung "
                         "(the device-resident sweep grid; the CPU "
                         "fallback rung scales this down to 8 to fit its "
                         "timeout)")
    ap.add_argument("--worker", action="store_true",
                    help="internal: run the measurement in-process")
    ap.add_argument("--timeout", type=int, default=0,
                    help="per-rung timeout override (seconds)")
    args = ap.parse_args()

    if args.worker:
        return worker(args)

    diags = []
    platform, probe_diags, cached_failure = probe_backend()
    diags += probe_diags

    cpu_mode = platform is None or platform == "cpu"
    if cpu_mode:
        # Accelerator missing or down: pin CPU so the worker cannot hang on
        # accelerator init, run one small rung (8 lanes: a 32-lane rung at
        # CPU round times would blow the rung timeout).
        rungs = [CPU_RUNG]
        env = dict(os.environ, JAX_PLATFORMS="cpu", GOSSIP_BENCH_FORCE_CPU="1")
        diags.append("accelerator unavailable -> CPU fallback" if platform
                     is None else "no accelerator present")
    else:
        rungs = LADDER
        env = dict(os.environ)
    lanes = (min(args.lane_sweep_lanes, 8) if cpu_mode
             else args.lane_sweep_lanes)

    if args.num_nodes > 0:  # manual rung
        rungs = [(args.num_nodes, args.origin_batch, args.iterations,
                  args.timeout or 900)]

    result = None
    for (n, o, iters, tmo) in rungs:
        result = run_rung(n, o, iters, args.warmup_timing,
                          args.timeout or tmo, env, diags, lanes=lanes)
        if result is not None:
            break

    if result is None and platform not in (None, "cpu"):
        # every accelerator rung failed -> last-ditch CPU number
        cpu_env = dict(os.environ, JAX_PLATFORMS="cpu",
                       GOSSIP_BENCH_FORCE_CPU="1")
        n, o, iters, tmo = CPU_RUNG
        result = run_rung(n, o, iters, args.warmup_timing, tmo, cpu_env,
                          diags, label="[cpu-fallback]",
                          lanes=min(args.lane_sweep_lanes, 8))

    if result is None:
        out = {
            "metric": "origin_iters_per_sec", "value": 0.0,
            "unit": "origin*iters/s", "vs_baseline": 0.0,
            "platform": platform or "unavailable", "error": "all rungs failed",
            "diagnostics": diags,
        }
        if cached_failure is not None:
            out["probe_cached_failure"] = cached_failure
        print(json.dumps(out))
        return 1

    if cached_failure is not None:
        # never silently report CPU numbers off a cached probe failure:
        # say why the accelerator was skipped and how stale that verdict is
        result["probe_cached_failure"] = cached_failure
    result["diagnostics"] = diags
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
