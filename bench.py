"""Benchmark: full five-verb gossip rounds, 10k-node cluster, batched origins.

Prints ONE JSON line:
  {"metric": "origin_iters_per_sec", "value": ..., "unit": "origin*iters/s",
   "vs_baseline": ...}

Baseline context (BASELINE.md): the north-star target is 10k nodes x ALL
origins x 1000 iterations in < 60 s on a v5e-8 — i.e. 166,667 origin-iters/s
across 8 chips, 20,833 per chip.  ``vs_baseline`` is measured single-chip
throughput over that per-chip share (>= 1.0 means the 8-chip target is met
by origin-parallel scaling, which is collective-free).
"""

import argparse
import json
import sys
import time

import numpy as np

PER_CHIP_TARGET = 166_667.0 / 8  # origin-iters/s


def synthetic_stakes(n, seed=0):
    """Heavy-tailed mainnet-like stake distribution (lognormal, ~5 orders of
    magnitude spread like the real validator set)."""
    rng = np.random.default_rng(seed)
    sol = np.exp(rng.normal(9.5, 2.0, n)).astype(np.int64) + 1
    return sol * 1_000_000_000


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-nodes", type=int, default=10_000)
    ap.add_argument("--origin-batch", type=int, default=32)
    ap.add_argument("--iterations", type=int, default=100)
    ap.add_argument("--warmup-timing", type=int, default=5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from gossip_sim_tpu.engine import (EngineParams, init_state,
                                       make_cluster_tables, run_rounds)

    platform = jax.devices()[0].platform
    if platform == "cpu":  # CI / no-accelerator fallback: keep it quick
        args.num_nodes = min(args.num_nodes, 1000)
        args.origin_batch = min(args.origin_batch, 4)
        args.iterations = min(args.iterations, 20)

    n, o = args.num_nodes, args.origin_batch
    tables = make_cluster_tables(synthetic_stakes(n))
    params = EngineParams(num_nodes=n, warm_up_rounds=0)
    origins = jnp.arange(o, dtype=jnp.int32)

    t0 = time.time()
    state = init_state(jax.random.PRNGKey(0), tables, origins, params)
    jax.block_until_ready(state)
    t_init = time.time() - t0

    # compile + protocol warm-up (also brings the prune/rotate paths live)
    state, rows = run_rounds(params, tables, origins, state,
                             args.warmup_timing)
    jax.block_until_ready(rows)

    t0 = time.time()
    state, rows = run_rounds(params, tables, origins, state, args.iterations,
                             start_it=args.warmup_timing)
    jax.block_until_ready(rows)
    dt = time.time() - t0

    value = o * args.iterations / dt
    cov = float(np.asarray(rows["coverage"]).mean())
    rmr = float(np.asarray(rows["rmr"]).mean())
    result = {
        "metric": "origin_iters_per_sec",
        "value": round(value, 2),
        "unit": "origin*iters/s",
        "vs_baseline": round(value / PER_CHIP_TARGET, 4),
        "platform": platform,
        "num_nodes": n,
        "origin_batch": o,
        "iterations": args.iterations,
        "elapsed_s": round(dt, 3),
        "init_s": round(t_init, 3),
        "coverage_mean": round(cov, 6),
        "rmr_mean": round(rmr, 6),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
